"""Resource-lifecycle checker: paired acquire/release discipline and
OS-resource cleanup on *all* paths, including exception paths
(docs/static_analysis.md "Resource-lifecycle rules").

Two rules:

``acquire-release`` — the serving stack's paired protocols must be
exception-safe:

* ``X.try_acquire(...)`` (the admission controller's slot protocol)
  must reach an ``X.release(...)`` in the same function or through a
  same-module callee, and at least one reachable release must sit in a
  ``finally`` block — an exception between admit and release otherwise
  leaks the slot forever (the limiter counts it in-flight until
  process death, exactly the PR 8 review class of bug);
* paired brackets that appear together in one function —
  ``X.begin()``/``X.end()``, ``X.begin_request()``/``X.end_request()``,
  and ``self._*inflight* += 1`` / ``-= 1`` — must put the closing half
  in a ``finally``. When only one half appears the pair is a
  cross-thread handoff (the pipeline semaphore acquired by the
  collector and released by the completer) and is NOT flagged: that
  discipline belongs to the race rules.

``resource-leak`` — ``open()``/``socket.socket()``/
``subprocess.Popen()``/``tempfile.TemporaryDirectory()`` (and friends)
must reach their cleanup (``close``/``terminate``/``cleanup``/...) on
every path: a ``with`` statement, a cleanup in a ``finally``, or
ownership escaping to the caller (returned, stored on ``self``/into a
container, passed to another component — whoever holds the object owns
the close). A cleanup that only sits on the fall-through path, with
calls in between that can raise, is flagged: that is the classic
``f = open(...); f.write(...); f.close()`` leak. Thread ``start()``
lifecycles are the existing ``thread-lifecycle`` rule's job and are
not re-checked here.
"""

from __future__ import annotations

import ast
import dataclasses

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.model import Finding
from predictionio_tpu.analysis.source import SourceModule

#: each module's findings depend only on that module's text —
#: cacheable per file (see analysis/cache.py)
PER_FILE = True

#: creator call -> (human kind, cleanup method names)
_CREATORS: dict[str, tuple[str, frozenset[str]]] = {
    "open": ("file", frozenset({"close"})),
    "io.open": ("file", frozenset({"close"})),
    "os.fdopen": ("file", frozenset({"close"})),
    "gzip.open": ("file", frozenset({"close"})),
    "bz2.open": ("file", frozenset({"close"})),
    "lzma.open": ("file", frozenset({"close"})),
    "tarfile.open": ("archive", frozenset({"close"})),
    "zipfile.ZipFile": ("archive", frozenset({"close"})),
    "socket.socket": (
        "socket", frozenset({"close", "shutdown", "detach"})
    ),
    "socket.create_connection": (
        "socket", frozenset({"close", "shutdown", "detach"})
    ),
    "subprocess.Popen": (
        "process",
        frozenset({"terminate", "kill", "wait", "communicate"}),
    ),
    "tempfile.TemporaryDirectory": (
        "temporary directory", frozenset({"cleanup"})
    ),
    "tempfile.NamedTemporaryFile": ("file", frozenset({"close"})),
}

#: acquire leaf -> matching release leaf, for brackets that must pair
#: exception-safely when both halves appear in one function
_PAIRS = {
    "try_acquire": "release",
    "begin": "end",
    "begin_request": "end_request",
}

_TRY_TYPES = tuple(
    t for t in (ast.Try, getattr(ast, "TryStar", None)) if t is not None
)

_DEF_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


@dataclasses.dataclass
class _CallSite:
    recv: str  # dotted receiver ("self._adm", "admission_ref")
    leaf: str  # method name
    node: ast.Call
    in_finally: bool


@dataclasses.dataclass
class _AugSite:
    target: str  # dotted target ("self._inflight")
    sign: str  # "+" or "-"
    node: ast.AugAssign
    in_finally: bool


@dataclasses.dataclass
class _CreateSite:
    node: ast.Call
    ctor: str
    kind: str
    cleanups: frozenset[str]


@dataclasses.dataclass
class _Scan:
    calls: list[_CallSite] = dataclasses.field(default_factory=list)
    augs: list[_AugSite] = dataclasses.field(default_factory=list)
    creators: list[_CreateSite] = dataclasses.field(default_factory=list)


def _scan_scope(body: list[ast.stmt]) -> _Scan:
    """Collect calls / augmented assigns / creator sites in one scope
    (a function body or the module body), tagging each with whether it
    executes inside a ``finally`` block, and never descending into
    nested function/class definitions (their own scopes)."""
    scan = _Scan()
    _scan_body(body, False, scan)
    return scan


def _scan_body(body: list, in_finally: bool, scan: _Scan) -> None:
    for stmt in body:
        if isinstance(stmt, _DEF_TYPES):
            continue
        if isinstance(stmt, _TRY_TYPES):
            _scan_body(stmt.body, in_finally, scan)
            for handler in stmt.handlers:
                _scan_body(handler.body, in_finally, scan)
            _scan_body(stmt.orelse, in_finally, scan)
            _scan_body(stmt.finalbody, True, scan)
            continue
        nested: list[ast.stmt] = []
        for field in ("body", "orelse"):
            nested.extend(getattr(stmt, field, ()) or ())
        for case in getattr(stmt, "cases", ()):  # ast.Match
            nested.extend(case.body)
        skip = set(map(id, nested))
        # seed with the statement node itself — it may BE the record
        # (AugAssign is the statement, not a child of one); the loop
        # expands children with the nested-body skip applied
        todo: list[ast.AST] = [stmt]
        while todo:
            cur = todo.pop()
            if isinstance(cur, _DEF_TYPES):
                continue
            _record(cur, in_finally, scan)
            todo.extend(
                c for c in ast.iter_child_nodes(cur) if id(c) not in skip
            )
        for sub in nested:
            _scan_body([sub], in_finally, scan)


def _record(node: ast.AST, in_finally: bool, scan: _Scan) -> None:
    if isinstance(node, ast.Call):
        dotted = astutil.dotted_name(node.func)
        if dotted in _CREATORS:
            kind, cleanups = _CREATORS[dotted]
            scan.creators.append(
                _CreateSite(
                    node=node, ctor=dotted, kind=kind, cleanups=cleanups
                )
            )
        if isinstance(node.func, ast.Attribute):
            recv = astutil.dotted_name(node.func.value)
            if recv:
                scan.calls.append(
                    _CallSite(
                        recv=recv,
                        leaf=node.func.attr,
                        node=node,
                        in_finally=in_finally,
                    )
                )
    elif isinstance(node, ast.AugAssign):
        target = astutil.dotted_name(node.target)
        if target and isinstance(node.op, (ast.Add, ast.Sub)):
            scan.augs.append(
                _AugSite(
                    target=target,
                    sign="+" if isinstance(node.op, ast.Add) else "-",
                    node=node,
                    in_finally=in_finally,
                )
            )


def _recv_leaf(recv: str) -> str:
    return recv.rsplit(".", 1)[-1]


# --------------------------------------------------------------------------
# acquire/release
# --------------------------------------------------------------------------


def _resolve_callee(call: ast.Call, ctx: str, index) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute) and isinstance(
        func.value, ast.Name
    ) and func.value.id in ("self", "cls"):
        owner = index.owner_class.get(ctx, "")
        if not owner:
            parts = ctx.split(".")
            for i in range(len(parts) - 1, 0, -1):
                owner = index.owner_class.get(".".join(parts[:i]), "")
                if owner:
                    break
        qual = f"{owner}.{func.attr}" if owner else func.attr
        return qual if qual in index.funcs else None
    if isinstance(func, ast.Name):
        for candidate in (f"{ctx}.{func.id}", func.id):
            if candidate in index.funcs:
                return candidate
    return None


def _release_summaries(
    scans: dict[str, _Scan], index
) -> dict[str, set[str]]:
    """{function qualname: receiver leafs it (transitively) releases}
    — a same-module fixpoint so ``finally: self._cleanup()`` counts
    when ``_cleanup`` does the actual ``release``."""
    releases: dict[str, set[str]] = {}
    callees: dict[str, set[str]] = {}
    for qual, scan in scans.items():
        releases[qual] = {
            _recv_leaf(c.recv)
            for c in scan.calls
            if c.leaf == "release"
        }
        callees[qual] = set()
        for c in scan.calls:
            resolved = _resolve_callee(c.node, qual, index)
            if resolved:
                callees[qual].add(resolved)
    changed = True
    while changed:
        changed = False
        for qual, outs in callees.items():
            for callee in outs:
                extra = releases.get(callee, set()) - releases[qual]
                if extra:
                    releases[qual] |= extra
                    changed = True
    return releases


def _check_acquire_release(
    mod: SourceModule,
    scans: dict[str, _Scan],
    index,
    findings: list[Finding],
) -> None:
    release_of = _release_summaries(scans, index)
    for qual, scan in scans.items():
        fn_leaf = qual.rsplit(".", 1)[-1] if qual else ""
        for site in scan.calls:
            if site.leaf != "try_acquire":
                continue
            if "acquire" in fn_leaf:
                # a delegating wrapper (def try_acquire: return
                # inner.try_acquire(...)) hands the obligation to ITS
                # caller
                continue
            leaf = _recv_leaf(site.recv)
            if any(
                leaf in release_of.get(nested, set())
                for nested in scans
                if nested.startswith(f"{qual}.")
            ):
                # the release lives in a nested function defined here
                # (a future done-callback, a closure handed to the
                # batcher): the obligation escapes into the callback —
                # its exception-safety is the callback runner's
                # contract, not this function's
                continue
            direct = [
                c for c in scan.calls
                if c.leaf == "release" and c.recv == site.recv
            ]
            via_callee = [
                c for c in scan.calls
                if leaf in release_of.get(
                    _resolve_callee(c.node, qual, index) or "", set()
                )
            ]
            if not direct and not via_callee:
                findings.append(_mk(
                    mod, "acquire-release", site.node, qual,
                    f"{site.recv}.try_acquire(...) is never paired "
                    "with a release on any path in this function or "
                    "its same-module callees — the slot leaks",
                ))
                continue
            if not any(c.in_finally for c in direct + via_callee):
                findings.append(_mk(
                    mod, "acquire-release", site.node, qual,
                    f"no {site.recv}.release(...) reachable from this "
                    "try_acquire sits in a finally block — an "
                    "exception between admit and release leaks the "
                    "slot",
                ))
        # paired brackets: both halves in one function
        for a_leaf, r_leaf in _PAIRS.items():
            if a_leaf == "try_acquire":
                continue  # handled above with callee propagation
            for site in scan.calls:
                if site.leaf != a_leaf:
                    continue
                closers = [
                    c for c in scan.calls
                    if c.leaf == r_leaf and c.recv == site.recv
                ]
                if closers and not any(c.in_finally for c in closers):
                    findings.append(_mk(
                        mod, "acquire-release", site.node, qual,
                        f"{site.recv}.{a_leaf}() is closed by "
                        f".{r_leaf}() on the fall-through path only — "
                        "put the closing call in a finally",
                    ))
        # inflight counters: += / -= on the same *inflight* field
        for aug in scan.augs:
            if aug.sign != "+" or "inflight" not in aug.target.lower():
                continue
            decs = [
                a for a in scan.augs
                if a.sign == "-" and a.target == aug.target
            ]
            if decs and not any(a.in_finally for a in decs):
                findings.append(_mk(
                    mod, "acquire-release", aug.node, qual,
                    f"{aug.target} += 1 is decremented on the "
                    "fall-through path only — an exception leaves the "
                    "gauge permanently high; decrement in a finally",
                ))


# --------------------------------------------------------------------------
# resource-leak
# --------------------------------------------------------------------------


def _in_with_or_return(node: ast.AST) -> bool:
    cur: ast.AST | None = node
    while cur is not None:
        parent = astutil.parent_of(cur)
        if isinstance(parent, ast.withitem):
            return True
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(parent, ast.stmt):
            return False
        cur = parent
    return False


def _binding_of(call: ast.Call) -> tuple[str, ast.AST | None]:
    """How the creator's result is bound: ("name", Name) for a plain
    local, ("attr", Attribute) for ``self.x = ...``, ("transfer", None)
    when it is immediately handed to another expression (call argument,
    container element, subscript store — ownership moves), or
    ("discard", None) for a bare expression statement."""
    cur: ast.AST = call
    parent = astutil.parent_of(cur)
    while isinstance(parent, (ast.Await, ast.IfExp, ast.BoolOp)):
        cur, parent = parent, astutil.parent_of(parent)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        target = parent.targets[0]
        if isinstance(target, ast.Name):
            return "name", target
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id in ("self", "cls"):
            return "attr", target
        return "transfer", None  # subscript / tuple target: container
    if isinstance(parent, (ast.AnnAssign, ast.NamedExpr)):
        target = parent.target
        if isinstance(target, ast.Name):
            return "name", target
        return "transfer", None
    if isinstance(parent, ast.Expr):
        return "discard", None
    # call argument, dict/list element, comparison operand, ...:
    # the resource flows into another owner
    return "transfer", None


def _function_node_of(mod: SourceModule, qual: str):
    if not qual:
        return mod.tree
    return mod.index().funcs.get(qual, mod.tree)


def _name_escapes(scope: ast.AST, name: str, after_line: int) -> bool:
    """Does local ``name`` escape the scope after its binding —
    returned, yielded, stored into an attribute/subscript/container,
    passed as a call argument, or captured by a nested function?"""
    for node in ast.walk(scope):
        if node is scope:
            continue  # the scope's own def is not a capture of itself
        if getattr(node, "lineno", 0) < after_line and not isinstance(
            node, _DEF_TYPES
        ):
            continue
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            # only the object ITSELF escaping counts (`return f`, or a
            # tuple containing it, handled by the container branch):
            # `return td.name` returns a derived value and drops the
            # resource on the floor
            value = getattr(node, "value", None)
            if value is not None and _mentions_bare(value, name):
                return True
        elif isinstance(node, ast.Assign):
            if any(
                not isinstance(t, ast.Name) for t in node.targets
            ) and _mentions(node.value, name):
                return True
        elif isinstance(node, ast.Call):
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if _mentions_bare(arg, name):
                    return True
        elif isinstance(node, _DEF_TYPES[:2]):
            if any(
                isinstance(sub, ast.Name) and sub.id == name
                for sub in ast.walk(node)
            ):
                return True
        elif isinstance(node, ast.Lambda):
            if any(
                isinstance(sub, ast.Name) and sub.id == name
                for sub in ast.walk(node)
            ):
                return True
        elif isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.Dict)):
            if isinstance(
                astutil.parent_of(node), (ast.Assign, ast.Return)
            ) and _mentions_bare_elts(node, name):
                return True
    return False


def _mentions(expr: ast.AST, name: str) -> bool:
    return any(
        isinstance(sub, ast.Name) and sub.id == name
        for sub in ast.walk(expr)
    )


def _mentions_bare(expr: ast.AST, name: str) -> bool:
    """``name`` used AS the argument (not just somewhere inside an
    expression computing something else — ``n.fileno()`` is a use,
    not a transfer)."""
    if isinstance(expr, ast.Name):
        return expr.id == name
    if isinstance(expr, ast.Starred):
        return _mentions_bare(expr.value, name)
    return False


def _mentions_bare_elts(node: ast.AST, name: str) -> bool:
    elts = list(getattr(node, "elts", ()) or ())
    elts.extend(getattr(node, "values", ()) or ())
    return any(_mentions_bare(e, name) for e in elts)


def _check_resources(
    mod: SourceModule,
    scans: dict[str, _Scan],
    findings: list[Finding],
) -> None:
    # (owner class, attr) -> cleaned anywhere in the module?
    attr_cleaned: set[tuple[str, str]] = set()
    index = mod.index()
    for qual, scan in scans.items():
        owner = index.owner_class.get(qual, "")
        for c in scan.calls:
            if c.recv.startswith(("self.", "cls.")):
                attr_cleaned.add((owner, _recv_leaf(c.recv), c.leaf))

    for qual, scan in scans.items():
        scope = _function_node_of(mod, qual)
        for site in scan.creators:
            if _in_with_or_return(site.node):
                continue
            binding, target = _binding_of(site.node)
            if binding == "transfer":
                continue
            if binding == "discard":
                findings.append(_mk(
                    mod, "resource-leak", site.node, qual,
                    f"{site.ctor}(...) result is discarded — the "
                    f"{site.kind} can never be closed",
                ))
                continue
            if binding == "attr":
                owner = index.owner_class.get(qual, "")
                attr = target.attr
                if not any(
                    (owner, attr, leaf) in attr_cleaned
                    for leaf in site.cleanups
                ):
                    findings.append(_mk(
                        mod, "resource-leak", site.node, qual,
                        f"{site.ctor}(...) stored on self.{attr} but "
                        f"no method of {owner or 'this class'} ever "
                        f"calls {'/'.join(sorted(site.cleanups))} on "
                        "it",
                    ))
                continue
            # plain local name
            name = target.id
            if _name_escapes(scope, name, site.node.lineno):
                continue
            cleanups = [
                c for c in scan.calls
                if c.recv == name and c.leaf in site.cleanups
                and c.node.lineno >= site.node.lineno
            ]
            if not cleanups:
                findings.append(_mk(
                    mod, "resource-leak", site.node, qual,
                    f"{site.ctor}(...) bound to {name!r} but "
                    f"{'/'.join(sorted(site.cleanups))} is never "
                    "called and the value never escapes — use a "
                    "with statement",
                ))
                continue
            if any(c.in_finally for c in cleanups):
                continue
            first_cleanup = min(c.node.lineno for c in cleanups)
            risky = any(
                site.node.lineno < c.node.lineno < first_cleanup
                for c in scan.calls
            )
            if risky:
                findings.append(_mk(
                    mod, "resource-leak", site.node, qual,
                    f"{name!r} ({site.kind}) is only closed on the "
                    "fall-through path — an exception in between "
                    "leaks it; use with, or close in a finally",
                ))


def _mk(
    mod: SourceModule, rule: str, node: ast.AST, qual: str, message: str
) -> Finding:
    return Finding(
        rule=rule,
        path=mod.rel_path,
        line=node.lineno,
        col=node.col_offset,
        message=message,
        context=qual,
        source=mod.source_line(node.lineno),
    )


def check(modules: list[SourceModule]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        index = mod.index()
        scans: dict[str, _Scan] = {
            "": _scan_scope(mod.tree.body)
        }
        for qual, fn in index.funcs.items():
            scans[qual] = _scan_scope(fn.body)
        _check_acquire_release(mod, scans, index, findings)
        _check_resources(mod, scans, findings)
    return findings
