"""HelloWorld template — the smallest possible engine.

Capability parity with the reference
``examples/experimental/scala-local-helloworld/HelloWorld.scala``
(and its java-local twin): training data is (day, temperature) pairs,
the model is the mean temperature per day, a query ``{"day": "Mon"}``
answers ``{"temperature": <mean>}``. The reference reads a CSV
(``data/helloworld/data.csv``); this version reads either the event
store ("report" events on entity type "day" carrying a ``temperature``
property) or a CSV file, whichever the params name.

Deliberately tiny, but still TPU-shaped: the per-day mean is a
``segment_sum`` on device — the same primitive every bigger aggregation
in this framework uses — so the tutorial teaches the real pattern.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    Params,
    register_engine,
)
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.utils.bimap import BiMap


@dataclasses.dataclass(frozen=True)
class HelloDataSourceParams(Params):
    app_name: str = ""       # read "report" events from this app…
    filepath: str = ""       # …or "day,temperature" CSV lines from a file
    event_name: str = "report"
    day_entity_type: str = "day"


@dataclasses.dataclass
class HelloTrainingData:
    days: np.ndarray          # [N] str
    temperatures: np.ndarray  # [N] float32


class HelloDataSource(DataSource):
    params_class = HelloDataSourceParams

    def read_training(self, ctx: ComputeContext) -> HelloTrainingData:
        p = self.params
        days, temps = [], []
        if p.filepath:
            with open(p.filepath) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    day, temp = line.split(",")
                    days.append(day.strip())
                    temps.append(float(temp))
        else:
            for event in EventStore().find(
                p.app_name,
                entity_type=p.day_entity_type,
                event_names=[p.event_name],
            ):
                days.append(event.entity_id)
                temps.append(float(event.properties.get("temperature")))
        if not days:
            raise ValueError("no temperature data found")
        return HelloTrainingData(
            days=np.asarray(days),
            temperatures=np.asarray(temps, np.float32),
        )


@dataclasses.dataclass
class HelloModel:
    day_map: BiMap
    means: np.ndarray  # [n_days] float32


@functools.partial(jax.jit, static_argnames=("n",))
def _segment_mean(codes: jax.Array, values: jax.Array, n: int):
    total = jax.ops.segment_sum(values, codes, num_segments=n)
    count = jax.ops.segment_sum(
        jnp.ones_like(values), codes, num_segments=n
    )
    return total / jnp.maximum(count, 1.0)


class HelloAlgorithm(Algorithm):

    def train(self, ctx: ComputeContext, pd: HelloTrainingData) -> HelloModel:
        day_map, codes = BiMap.string_int_with_codes(pd.days)
        means = _segment_mean(
            jnp.asarray(codes), jnp.asarray(pd.temperatures), len(day_map)
        )
        return HelloModel(day_map=day_map, means=np.asarray(means))

    def predict(self, model: HelloModel, query: dict) -> dict:
        idx = model.day_map.get(str(query.get("day", "")), None)
        if idx is None:
            return {"temperature": None}
        return {"temperature": float(model.means[idx])}


def helloworld_engine() -> Engine:
    return Engine(
        HelloDataSource,
        IdentityPreparator,
        {"hello": HelloAlgorithm},
        FirstServing,
    )


register_engine("helloworld", helloworld_engine)
