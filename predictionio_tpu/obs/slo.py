"""SLO burn-rate monitor: per-criticality-class availability and
latency objectives tracked over multi-window rolling rates.

Every served request is scored good or bad against its class
objective (``good`` = non-5xx, not shed with 429, and under the
class latency threshold). Goods and bads accumulate into coarse
time buckets on the monotonic clock, and the monitor derives the
SRE-style *burn rate* over a short and a long window:

    burn = bad_fraction_in_window / (1 - availability_target)

``burn == 1`` means the error budget is being consumed exactly at the
sustainable rate; ``burn == 14`` on the short window is the classic
page-now threshold. Exported per class as
``pio_slo_burn_rate{class,window}`` and
``pio_slo_budget_remaining{class}`` (scrape-time gauges), plus the
mergeable ``pio_slo_requests_total{class,outcome}`` counter so the
router can compute *fleet-level* burn from federated counter deltas
without seeing individual requests.

Objectives are env-configurable (``PIO_SLO_<CLASS>_AVAILABILITY``,
``PIO_SLO_<CLASS>_LATENCY_MS``, ``PIO_SLO_SHORT_WINDOW_S``,
``PIO_SLO_LONG_WINDOW_S``) — see ``docs/observability.md``.

Stdlib-only, like the rest of ``obs/``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable

from predictionio_tpu.obs import timeline
from predictionio_tpu.obs.registry import MetricRegistry

#: short-window burn rate at which the incident timeline records an
#: ``slo_burn_alert`` — the classic page-now threshold
PAGE_BURN_RATE = 14.0

#: criticality classes tracked, mirroring ``serving.admission``
#: (admission is not imported: obs/ stays dependency-free)
CRITICAL = "critical"
DEFAULT = "default"
SHEDDABLE = "sheddable"
CLASSES = (CRITICAL, DEFAULT, SHEDDABLE)

WINDOWS = ("short", "long")

#: accumulation granularity — fine enough that a 60 s short window
#: has 12 buckets, coarse enough that pruning stays O(windows)
_BUCKET_S = 5.0

_DEFAULT_AVAILABILITY = {
    CRITICAL: 0.999,
    DEFAULT: 0.99,
    SHEDDABLE: 0.95,
}
_DEFAULT_LATENCY_MS = {
    CRITICAL: 500.0,
    DEFAULT: 1000.0,
    SHEDDABLE: 2000.0,
}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass(frozen=True)
class Objective:
    """One class's SLO: availability target plus a latency threshold
    a request must beat to count as good."""

    availability: float
    latency_s: float

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.availability)


def objectives_from_env() -> dict[str, Objective]:
    out = {}
    for cls in CLASSES:
        upper = cls.upper()
        out[cls] = Objective(
            availability=min(
                1.0 - 1e-9,
                _env_float(
                    f"PIO_SLO_{upper}_AVAILABILITY",
                    _DEFAULT_AVAILABILITY[cls],
                ),
            ),
            latency_s=_env_float(
                f"PIO_SLO_{upper}_LATENCY_MS",
                _DEFAULT_LATENCY_MS[cls],
            )
            / 1000.0,
        )
    return out


class SLOMonitor:
    """Rolling good/bad rates per criticality class with short- and
    long-window burn-rate derivation.

    Servers feed it per-request via :meth:`observe` (wired inside the
    HTTP server's telemetry tail); the router feeds it *deltas* of
    federated ``pio_slo_requests_total`` counters via :meth:`ingest`
    to get the fleet-level view. Thread-safe; gauges evaluate at
    scrape time.
    """

    def __init__(
        self,
        registry: MetricRegistry | None = None,
        *,
        objectives: dict[str, Objective] | None = None,
        short_window_s: float | None = None,
        long_window_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        export_counter: bool = True,
    ) -> None:
        self._objectives = dict(objectives or objectives_from_env())
        short = (
            short_window_s
            if short_window_s is not None
            else _env_float("PIO_SLO_SHORT_WINDOW_S", 60.0)
        )
        long = (
            long_window_s
            if long_window_s is not None
            else _env_float("PIO_SLO_LONG_WINDOW_S", 600.0)
        )
        self._windows = {
            "short": max(_BUCKET_S, short),
            "long": max(_BUCKET_S, short, long),
        }
        self._clock = clock
        self._lock = threading.Lock()
        # class -> {bucket index -> [good, bad]}
        self._buckets: dict[str, dict[int, list[float]]] = {
            cls: {} for cls in self._objectives
        }
        #: classes currently past the page-now burn threshold — the
        #: incident-timeline alert fires on the crossing, not per
        #: request, and clears with hysteresis at half the threshold
        self._alerting: set[str] = set()
        self._requests = None
        if registry is not None:
            if export_counter:
                self._requests = registry.counter(
                    "pio_slo_requests_total",
                    "Requests scored against the class SLO "
                    "(outcome=good|bad)",
                    ("class", "outcome"),
                )
            burn = registry.gauge(
                "pio_slo_burn_rate",
                "Error-budget burn rate per criticality class "
                "(1.0 = burning exactly at budget)",
                ("class", "window"),
            )
            remaining = registry.gauge(
                "pio_slo_budget_remaining",
                "Fraction of the class error budget left within the "
                "long window",
                ("class",),
            )
            for cls in self._objectives:
                for window in WINDOWS:
                    burn.labels(cls, window).set_function(
                        self._burn_fn(cls, window)
                    )
                remaining.labels(cls).set_function(
                    self._remaining_fn(cls)
                )

    def _burn_fn(self, cls: str, window: str):
        return lambda: self.burn_rate(cls, window)

    def _remaining_fn(self, cls: str):
        return lambda: self.budget_remaining(cls)

    # -- ingestion ----------------------------------------------------

    def objective(self, criticality: str) -> Objective:
        return self._objectives.get(
            criticality, self._objectives[DEFAULT]
        )

    def observe(
        self, criticality: str, status: int, elapsed_s: float
    ) -> None:
        """Score one finished request against its class objective."""
        cls = (
            criticality
            if criticality in self._objectives
            else DEFAULT
        )
        obj = self._objectives[cls]
        good = (
            status < 500
            and status != 429
            and elapsed_s <= obj.latency_s
        )
        self.ingest(cls, good=float(good), bad=float(not good))

    def ingest(self, cls: str, good: float, bad: float) -> None:
        """Add pre-scored counts (federated counter deltas on the
        router, or a test fixture)."""
        if good <= 0.0 and bad <= 0.0:
            return
        if cls not in self._objectives:
            cls = DEFAULT
        idx = int(self._clock() / _BUCKET_S)
        with self._lock:
            bucket = self._buckets[cls].setdefault(idx, [0.0, 0.0])
            bucket[0] += max(0.0, good)
            bucket[1] += max(0.0, bad)
            self._prune(cls, idx)
        if self._requests is not None:
            if good > 0.0:
                self._requests.labels(cls, "good").inc(good)
            if bad > 0.0:
                self._requests.labels(cls, "bad").inc(bad)
        self._check_burn(cls)

    def _check_burn(self, cls: str) -> None:
        """Emit an incident-timeline event when the class's
        short-window burn rate crosses the classic page-now threshold
        (burn 14 ~= the budget gone in <2 days at a 30-day window);
        clears with hysteresis at half the threshold so a rate
        hovering at the line doesn't flap events."""
        burn = self.burn_rate(cls, "short")
        fire = 0
        with self._lock:
            if burn >= PAGE_BURN_RATE and cls not in self._alerting:
                self._alerting.add(cls)
                fire = 1
            elif cls in self._alerting and burn < PAGE_BURN_RATE / 2.0:
                self._alerting.discard(cls)
                fire = -1
        if fire > 0:
            timeline.get_timeline().record(
                "slo_burn_alert",
                f"class {cls!r} short-window burn rate {burn:.1f}x is "
                f"past the page threshold ({PAGE_BURN_RATE:.0f}x)",
                severity=timeline.ERROR,
                **{"class": cls, "burn": round(burn, 2)},
            )
        elif fire < 0:
            timeline.get_timeline().record(
                "slo_burn_alert",
                f"class {cls!r} burn rate recovered "
                f"({burn:.1f}x, below {PAGE_BURN_RATE / 2.0:.0f}x)",
                **{"class": cls, "burn": round(burn, 2)},
            )

    def _prune(self, cls: str, now_idx: int) -> None:
        horizon = now_idx - int(self._windows["long"] / _BUCKET_S) - 1
        buckets = self._buckets[cls]
        for idx in [i for i in buckets if i < horizon]:
            del buckets[idx]

    # -- derivation ---------------------------------------------------

    def _window_counts(
        self, cls: str, window_s: float
    ) -> tuple[float, float]:
        now_idx = int(self._clock() / _BUCKET_S)
        first = now_idx - int(window_s / _BUCKET_S) + 1
        good = bad = 0.0
        with self._lock:
            for idx, (g, b) in self._buckets.get(cls, {}).items():
                if first <= idx <= now_idx:
                    good += g
                    bad += b
        return good, bad

    def burn_rate(self, cls: str, window: str = "short") -> float:
        """Bad fraction over the window divided by the error budget;
        0.0 when the window is empty (no traffic burns nothing)."""
        if cls not in self._objectives:
            return 0.0
        good, bad = self._window_counts(
            cls, self._windows.get(window, self._windows["short"])
        )
        total = good + bad
        if total <= 0.0:
            return 0.0
        return (bad / total) / self._objectives[cls].error_budget

    def budget_remaining(self, cls: str) -> float:
        """Error-budget fraction left within the long window — 1.0
        untouched, 0.0 fully burned (clamped)."""
        return min(
            1.0, max(0.0, 1.0 - self.burn_rate(cls, "long"))
        )

    def max_burn_rate(self, window: str = "short") -> float:
        """Worst short-window burn across classes — the scalar the
        autoscaler keys scale-up on."""
        return max(
            (
                self.burn_rate(cls, window)
                for cls in self._objectives
            ),
            default=0.0,
        )

    def snapshot(self) -> dict:
        """JSON-friendly burn/budget state (status endpoints, CLI)."""
        out = {}
        for cls in self._objectives:
            out[cls] = {
                "burnShort": round(self.burn_rate(cls, "short"), 4),
                "burnLong": round(self.burn_rate(cls, "long"), 4),
                "budgetRemaining": round(
                    self.budget_remaining(cls), 4
                ),
                "availability": self._objectives[cls].availability,
                "latencyMs": round(
                    self._objectives[cls].latency_s * 1000.0, 3
                ),
            }
        return out
