"""Continuous-training smoke test: rehearse the crash-safe model
lifecycle end to end (docs/training.md), under continuous traffic with
ZERO non-200 responses. Proves, in order:

1. **kill -9 resume** — a supervised `pio-tpu trainer` child is
   SIGKILLed mid-epoch (PIO_TRAIN_CHAOS stretches epochs so the window
   is deterministic); the supervisor respawns it and the retrain
   RESUMES from the latest ALS checkpoint (state file records
   ``resumedFromIteration`` ≥ the iteration observed at kill — never a
   from-scratch restart);
2. **fold-in freshness** — events for a brand-new user trigger an
   incremental fold-in generation (parent pointer intact) and the
   event→serving latency for that user is measured and appended to
   SERVING_BENCH.json (schema serving-bench/v1);
3. **quarantine + last-good** — a flipped bit in the latest published
   artifact is caught by checksum verification at reload: the corrupt
   generation is moved aside (``pio_model_quarantined_total``) and the
   last-good generation keeps serving;
4. **canary rejection** — a NaN-factor generation is staged, shadow-
   scored on live traffic, and REJECTED at the gate; users never see
   it;
5. **automatic rollback** — a generation that passes the gate
   (identical predictions) but regresses post-promotion latency is
   promoted, detected by the regression watch, and rolled back — all
   transitions visible as ``pio_model_generation`` /
   ``pio_shadow_divergence`` / ``pio_canary_state`` moves in
   /metrics.json.

Run by ``scripts/check.sh`` next to the other smokes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

WORK = tempfile.mkdtemp(prefix="pio-trainer-smoke-")
STORAGE_ENV = {
    "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
    "PIO_STORAGE_SOURCES_SQL_PATH": os.path.join(WORK, "pio.sqlite"),
    "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
    "PIO_STORAGE_SOURCES_FS_PATH": os.path.join(WORK, "models"),
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
}
os.environ.update(STORAGE_ENV)

failures: list[str] = []


def check(cond: bool, label: str) -> None:
    print(("ok   " if cond else "FAIL ") + label, flush=True)
    if not cond:
        failures.append(label)


def http_json(url, body=None, timeout=15):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        method="POST" if body is not None else "GET",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def metric_value(base, name, default=0.0):
    status, data = http_json(f"{base}/metrics.json")
    family = (data or {}).get(name)
    if not isinstance(family, dict):
        return default
    samples = family.get("samples") or []
    total = 0.0
    for s in samples:
        total += s.get("value", s.get("count", 0.0)) or 0.0
    return total if samples else default


class Traffic:
    """Continuous background load; every response must be 200."""

    def __init__(self, base: str, body: dict, rate_hz: float = 80.0):
        self.base = base
        self.body = body
        self.rate = rate_hz
        self.ok = 0
        self.non_200: list[tuple[int, object]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="smoke-traffic", daemon=True
        )
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                status, out = http_json(
                    f"{self.base}/queries.json", self.body, timeout=30
                )
            except OSError:
                continue  # server not up yet / shutting down
            if status == 200:
                self.ok += 1
            else:
                self.non_200.append((status, out))
            self._stop.wait(1.0 / self.rate)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


def wait_for(predicate, timeout_s, label, poll_s=0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
    check(False, f"timed out waiting for {label}")
    return None


# --------------------------------------------------------------------------
# Phase A: supervised trainer — kill -9 resume + fold-in freshness
# --------------------------------------------------------------------------


def phase_trainer() -> None:
    import numpy as np

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.ops import als as als_ops

    storage = get_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="smoke"))
    events = storage.get_events()
    events.init(app_id)
    for u in range(10):
        for i in range(6):
            events.insert(
                Event(
                    event="rate", entity_type="user",
                    entity_id=f"u{u}", target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties={"rating": 1.0 + (u + i) % 3},
                ),
                app_id,
            )

    variant_path = os.path.join(WORK, "engine.json")
    with open(variant_path, "w") as f:
        json.dump(
            {
                "engineFactory": "recommendation",
                "id": "rec-smoke",
                "datasource": {"params": {"app_name": "smoke"}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": 8,
                            "num_iterations": 30,
                            "block_len": 8,
                        },
                    }
                ],
            },
            f,
        )

    ckpt_dir = os.path.join(WORK, "ckpt")
    child_env = {
        **os.environ,
        # stretch each 2-iteration dispatch chunk so SIGKILL lands
        # mid-train deterministically
        "PIO_TRAIN_CHAOS": "epoch_sleep:0.3",
    }
    supervisor = subprocess.Popen(
        [
            sys.executable, "-m", "predictionio_tpu.cli.main", "trainer",
            "--engine", "recommendation", "--variant", variant_path,
            "--engine-id", "rec-smoke", "--app", "smoke",
            "--poll-interval", "0.3", "--min-new-events", "1",
            "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "2",
        ],
        env=child_env,
    )
    try:
        # 1. wait for a mid-train checkpoint, then kill -9 the child
        ckpt_file = als_ops.checkpoint_path(ckpt_dir)
        wait_for(
            lambda: als_ops.peek_checkpoint_iteration(ckpt_dir) >= 4,
            90, "mid-train checkpoint",
        )
        iter_at_kill = als_ops.peek_checkpoint_iteration(ckpt_dir)
        pid_file = os.path.join(ckpt_dir, "trainer.pid")
        with open(pid_file) as f:
            child_pid = int(f.read().strip())
        check(
            child_pid != supervisor.pid,
            "supervisor runs the trainer in a separate child process",
        )
        os.kill(child_pid, signal.SIGKILL)
        print(
            f"     killed -9 trainer pid {child_pid} at iteration "
            f"{iter_at_kill}", flush=True,
        )

        # 2. the supervisor respawns; the retrain resumes and completes
        instances = storage.get_meta_data_engine_instances()

        def completed():
            return instances.get_latest_completed(
                "rec-smoke", "1", "default"
            )

        first_gen = wait_for(completed, 120, "resumed retrain COMPLETED")
        state_path = os.path.join(ckpt_dir, "trainer_state.json")

        def trainer_state():
            try:
                with open(state_path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                return {}

        def finalized_state():
            s = trainer_state()
            # "publishing" is the crash-recoverable intermediate phase:
            # wait for the finalized ("idle") state before asserting
            return s if (
                s.get("lastInstanceId") and s.get("phase") == "idle"
            ) else None

        state = wait_for(finalized_state, 30, "trainer state file") or {}
        resumed = int(state.get("resumedFromIteration", -1))
        check(
            resumed >= iter_at_kill > 0,
            f"trainer resumed from checkpoint iteration {resumed} >= "
            f"{iter_at_kill} at kill (no from-scratch restart)",
        )
        check(
            int(state.get("fullTrains", 0)) == 1,
            "exactly one COMPLETED full train across both incarnations",
        )
        check(
            not os.path.exists(ckpt_file),
            "checkpoint cleared after the COMPLETED train",
        )

        # 3. serve the generation under continuous traffic
        from predictionio_tpu.models.recommendation import (
            recommendation_engine,
        )
        from predictionio_tpu.serving.engine_server import EngineServer

        engine = recommendation_engine()
        with open(variant_path) as f:
            params = engine.params_from_variant(json.load(f))
        server = EngineServer(
            engine, params, engine_id="rec-smoke",
            storage=storage, max_wait_ms=0.5,
        )
        http = server.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        traffic = Traffic(base, {"user": "u1", "num": 3})
        try:
            status, out = http_json(
                f"{base}/queries.json", {"user": "u1", "num": 3}
            )
            check(
                status == 200 and out.get("itemScores"),
                "known user served from the trainer's generation",
            )

            # 4. fold-in freshness: events for a NEW user → generation →
            #    reload → served, clocked end to end
            t0 = time.monotonic()
            for item in ("i0", "i1"):
                events.insert(
                    Event(
                        event="rate", entity_type="user",
                        entity_id="u_new", target_entity_type="item",
                        target_entity_id=item,
                        properties={"rating": 2.0},
                    ),
                    app_id,
                )

            def fold_in_gen():
                latest = completed()
                if latest and latest.id != first_gen.id:
                    return latest
                return None

            gen = wait_for(fold_in_gen, 60, "fold-in generation")
            freshness = None
            if gen is not None:
                check(
                    gen.env.get("foldIn", "").startswith("users=1"),
                    f"fold-in generation published ({gen.env.get('foldIn')}"
                    f", parent={gen.env.get('parent', '?')[:8]}…)",
                )
                status, _ = http_json(f"{base}/reload", body={})
                check(status == 200, "hot reload picked up the fold-in")

                def new_user_served():
                    s, out = http_json(
                        f"{base}/queries.json",
                        {"user": "u_new", "num": 3},
                    )
                    return s == 200 and out.get("itemScores")

                if wait_for(new_user_served, 30, "new user served"):
                    freshness = time.monotonic() - t0
                    check(
                        True,
                        f"event→serving freshness for fold-in: "
                        f"{freshness:.2f}s",
                    )
            if freshness is not None:
                import serving_bench

                serving_bench.persist_record(
                    {
                        "bench": "trainer-freshness",
                        "mode": "fold-in",
                        "freshnessSec": round(freshness, 3),
                        "newUserEvents": 2,
                        "pass": True,
                    },
                    os.path.join(REPO, "SERVING_BENCH.json"),
                )
                print(
                    "     freshness recorded to SERVING_BENCH.json",
                    flush=True,
                )
        finally:
            traffic.stop()
            http.shutdown()
        check(
            not traffic.non_200,
            f"zero non-200s during trainer phase "
            f"({traffic.ok} requests; first bad: "
            f"{traffic.non_200[:1]})",
        )
    finally:
        supervisor.send_signal(signal.SIGTERM)
        try:
            supervisor.wait(timeout=30)
        except subprocess.TimeoutExpired:
            supervisor.kill()


# --------------------------------------------------------------------------
# Phase B: canary gate — quarantine, NaN rejection, rollback
# --------------------------------------------------------------------------


def phase_canary() -> None:
    import glob

    from predictionio_tpu.core import (
        Algorithm,
        DataSource,
        Engine,
        EngineParams,
        Params,
        Preparator,
        Serving,
    )
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.parallel.mesh import ComputeContext
    from predictionio_tpu.serving.canary import CanaryConfig
    from predictionio_tpu.serving.engine_server import EngineServer

    @dataclasses.dataclass(frozen=True)
    class P(Params):
        pass

    class Src(DataSource):
        params_class = P

        def read_training(self, ctx):
            return {}

    class Prep(Preparator):
        params_class = P

        def prepare(self, ctx, td):
            return td

    class GenAlgo(Algorithm):
        """Model value frozen at train time from class attrs, so each
        run_train publishes an observably different generation."""

        params_class = P
        train_value = 1.0
        train_slow_s = 0.0

        def train(self, ctx, pd):
            return {
                "value": type(self).train_value,
                "slow_s": type(self).train_slow_s,
            }

        def predict(self, model, query):
            return self.batch_predict(model, [query])[0]

        def batch_predict(self, model, queries):
            if model["slow_s"]:
                time.sleep(model["slow_s"])
            return [{"result": model["value"]} for _ in queries]

    class First(Serving):
        params_class = P

        def serve(self, query, predictions):
            return predictions[0]

    storage = get_storage()
    ctx = ComputeContext.create(batch="canary-smoke")
    engine = Engine(Src, Prep, GenAlgo, First)
    params = EngineParams(
        data_source=("", P()), preparator=("", P()),
        algorithms=[("", P())], serving=("", P()),
    )

    def train():
        return run_train(
            engine, params, engine_id="cnry-smoke", ctx=ctx,
            storage=storage,
        )

    g1 = train()
    config = CanaryConfig(
        shadow_sample=1.0, min_shadow=5, max_divergence=0.05,
        watch_min_requests=10, watch_s=0.5, latency_factor=4.0,
        error_rate_limit=0.2, shadow_timeout_s=10.0,
    )
    server = EngineServer(
        engine, params, engine_id="cnry-smoke", storage=storage,
        ctx=ctx, canary=config, max_wait_ms=0.5,
    )
    http = server.serve(host="127.0.0.1", port=0)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    traffic = Traffic(base, {"x": 1})
    try:
        gen_before = metric_value(base, "pio_model_generation")

        # -- corrupt artifact → quarantine + last-good serve --
        g2 = train()
        blob_path = glob.glob(
            os.path.join(WORK, "models", f"pio_model_{g2}.bin")
        )
        check(bool(blob_path), "published artifact on localfs")
        with open(blob_path[0], "r+b") as f:
            f.seek(10)
            byte = f.read(1)
            f.seek(10)
            f.write(bytes([byte[0] ^ 0xFF]))  # one flipped bit-pattern
        status, body = http_json(f"{base}/reload", body={})
        check(
            status == 200 and "already serving" in body.get("message", ""),
            "corrupt generation never staged: reload fell back to "
            "last-good",
        )
        status, data = http_json(base)
        check(
            data.get("engineInstanceId") == g1,
            "last-good generation still serving after corruption",
        )
        check(
            metric_value(base, "pio_model_quarantined_total") >= 1,
            "corrupt generation quarantined "
            "(pio_model_quarantined_total >= 1)",
        )
        quarantined = glob.glob(
            os.path.join(WORK, "models", "*.quarantined.*")
        )
        check(bool(quarantined), "corrupt blob moved aside on disk")

        # -- NaN-factor generation rejected at the canary gate --
        GenAlgo.train_value = float("nan")
        train()
        status, body = http_json(f"{base}/reload", body={})
        check(status == 202, "NaN generation staged as canary (202)")
        wait_for(
            lambda: (server._last_canary or {}).get("state") == "rejected",
            60, "canary rejection",
        )
        status, data = http_json(base)
        check(
            data.get("engineInstanceId") == g1,
            "NaN generation rejected at the gate; last-good serving",
        )
        check(
            "NaN" in (server._last_canary or {}).get("reason", ""),
            "rejection reason names the NaN",
        )

        # -- slow generation: promoted, then auto-rolled-back --
        GenAlgo.train_value = 1.0  # identical output: gate passes
        GenAlgo.train_slow_s = 0.06
        g4 = train()
        status, body = http_json(f"{base}/reload", body={})
        check(status == 202, "slow generation staged as canary (202)")
        promoted = wait_for(
            lambda: http_json(base)[1].get("engineInstanceId") == g4,
            60, "canary promotion",
        )
        check(bool(promoted), "slow generation passed the gate and promoted")
        wait_for(
            lambda: (server._last_canary or {}).get("state")
            == "rolled_back",
            60, "automatic rollback",
        )
        status, data = http_json(base)
        check(
            data.get("engineInstanceId") == g1,
            "rollback restored the previous generation",
        )
        check(
            "latency" in (server._last_canary or {}).get("reason", ""),
            "rollback reason names the latency regression",
        )

        # -- lifecycle visible in /metrics.json --
        gen_after = metric_value(base, "pio_model_generation")
        check(
            gen_after >= gen_before + 2,
            f"pio_model_generation advanced {gen_before} → {gen_after} "
            "(promotion + rollback each visible)",
        )
        status, metrics = http_json(f"{base}/metrics.json")
        shadow = (metrics or {}).get("pio_shadow_divergence") or {}
        shadow_count = sum(
            s.get("count", 0) for s in shadow.get("samples", [])
        )
        check(
            shadow_count >= config.min_shadow,
            f"pio_shadow_divergence recorded {shadow_count} shadow "
            "comparisons",
        )
        check(
            metric_value(base, "pio_model_age_seconds") >= 0,
            "pio_model_age_seconds exported",
        )
    finally:
        traffic.stop()
        http.shutdown()
    check(
        not traffic.non_200,
        f"zero non-200s across quarantine/rejection/rollback "
        f"({traffic.ok} requests; first bad: {traffic.non_200[:1]})",
    )


def main() -> int:
    t0 = time.monotonic()
    print("== trainer smoke: crash-safe continuous training ==", flush=True)
    phase_trainer()
    print("== canary smoke: quarantine / rejection / rollback ==",
          flush=True)
    phase_canary()
    took = time.monotonic() - t0
    if failures:
        print(f"\nFAILED {len(failures)} check(s) in {took:.1f}s:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"\nall checks passed in {took:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
