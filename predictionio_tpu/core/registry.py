"""Engine registry — the reflection replacement.

The reference loads engine factories by runtime reflection on class names
(``WorkflowUtils.getEngine``, workflow/WorkflowUtils.scala:61-129). Here
factories register by name — explicitly, or implicitly by dotted import
path ``"package.module:factory"`` which the registry resolves on demand
(so templates living anywhere on PYTHONPATH work like the reference's
classpath-addressed factories). SURVEY.md §7 hard-part (e).
"""

from __future__ import annotations

import importlib
from typing import Callable

from predictionio_tpu.core.engine import Engine

EngineFactory = Callable[[], Engine]

_REGISTRY: dict[str, EngineFactory] = {}


def register_engine(name: str, factory: EngineFactory | None = None):
    """Register an engine factory; usable as a decorator."""

    def _register(f: EngineFactory) -> EngineFactory:
        _REGISTRY[name] = f
        return f

    if factory is not None:
        return _register(factory)
    return _register


def engine_registry() -> dict[str, EngineFactory]:
    return dict(_REGISTRY)


def resolve_engine_factory(name: str) -> EngineFactory:
    """Look up a registered name, or import ``"pkg.module:attr"`` /
    ``"pkg.module.attr"`` dotted paths."""
    # built-in templates self-register on import
    import predictionio_tpu.models  # noqa: F401

    if name in _REGISTRY:
        return _REGISTRY[name]
    module_name, sep, attr = name.partition(":")
    if not sep:
        module_name, _, attr = name.rpartition(".")
    if module_name:
        try:
            module = importlib.import_module(module_name)
            factory = getattr(module, attr)
        except (ImportError, AttributeError) as e:
            raise KeyError(
                f"engine factory {name!r} not registered and not importable: {e}"
            ) from e
        if name not in _REGISTRY:
            _REGISTRY[name] = factory
        return factory
    raise KeyError(
        f"engine factory {name!r} not registered; known: {sorted(_REGISTRY)}"
    )
