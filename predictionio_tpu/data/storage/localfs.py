"""Local-filesystem model blob store.

Counterpart of the reference's ``localfs`` backend
(``data/.../storage/localfs/LocalFSModels.scala``, model blobs as files
under ``PIO_FS_BASEDIR``). Model checkpoints written by orbax (sharded
array checkpoints) also live under this root — see
:mod:`predictionio_tpu.core.persistence`.

Durability contract (docs/training.md "Model generations"): every
insert is write-to-unique-tmp → flush → fsync → rename within the same
directory, then a best-effort directory fsync. Two racing publishers
each own a distinct tmp file, so concurrent inserts of the same id
resolve to one writer's complete bytes — never an interleaving — and a
crash mid-write leaves only a ``.tmp.*`` turd that no reader opens.
"""

from __future__ import annotations

import os
import secrets

from predictionio_tpu.data.storage.base import Model, ModelsBackend


def _fsync_dir(path: str) -> None:
    """Persist a rename against power loss; best-effort on filesystems
    (or platforms) whose directories cannot be opened for fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Atomic, durable byte write: unique same-directory tmp + fsync +
    rename + directory fsync. Shared by the model store and the trainer
    state file."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    # unique per writer: two concurrent publishers must not share a tmp
    tmp = f"{path}.tmp.{os.getpid()}.{secrets.token_hex(4)}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(directory)


class LocalFSModels(ModelsBackend):
    def __init__(self, config: dict | None = None):
        config = config or {}
        base = config.get("PATH") or os.path.join(
            os.environ.get(
                "PIO_FS_BASEDIR",
                os.path.join(os.path.expanduser("~"), ".piotpu"),
            ),
            "models",
        )
        os.makedirs(base, exist_ok=True)
        self._base = base

    def _path(self, model_id: str) -> str:
        safe = model_id.replace("/", "_")
        return os.path.join(self._base, f"pio_model_{safe}.bin")

    def insert(self, model: Model) -> None:
        atomic_write_bytes(self._path(model.id), model.models)

    def get(self, model_id: str) -> Model | None:
        try:
            with open(self._path(model_id), "rb") as f:
                return Model(id=model_id, models=f.read())
        except FileNotFoundError:
            return None

    def delete(self, model_id: str) -> bool:
        try:
            os.remove(self._path(model_id))
            return True
        except FileNotFoundError:
            return False

    def list_ids(self) -> list[str] | None:
        # `/` in an id is mangled to `_` by `_path`, so a slash-bearing
        # id round-trips lossy; generation ids never contain slashes,
        # and quarantined blobs (suffixed filenames) are excluded.
        ids = []
        for name in os.listdir(self._base):
            if name.startswith("pio_model_") and name.endswith(".bin"):
                ids.append(name[len("pio_model_"):-len(".bin")])
        return sorted(ids)

    def quarantine(self, model_id: str) -> bool:
        """Atomic move-aside of a corrupt blob: the original id stops
        resolving in one rename (no read-copy-delete window), and the
        bytes survive under ``.quarantined.<token>`` for forensics."""
        src = self._path(model_id)
        dst = f"{src}.quarantined.{secrets.token_hex(4)}"
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            return False
        _fsync_dir(os.path.dirname(src))
        return True
