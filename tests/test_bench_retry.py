"""bench.py retry orchestration — simulated failures, no subprocesses.

Guards the failure mode that erased rounds 1/2's perf records: a hung
worker ("timed out after Ns") must be retried, a dead tunnel must fail
fast in the pre-flight probe, and a cpu-fallback worker must not be
recorded as a TPU number."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def _ok_probe():
    return {"ok": True, "backend": "tpu"}, None


def _tpu_result():
    return {"seconds": 0.05, "backend": "tpu", "workload": "w"}, None


class _Script:
    """run_worker stub driven by a list of (side-prefix, response)."""

    def __init__(self, responses):
        self.responses = list(responses)
        self.calls = []

    def __call__(self, side, scale, timeout):
        self.calls.append((side, timeout))
        assert self.responses, f"unexpected extra call: {side}"
        return self.responses.pop(0)


def test_worker_timeout_with_live_backend_skips_reprobe():
    """The exact round-1/2 killer: a full run hangs MID-WORKLOAD (its
    phase tail proves the backend was up), then succeeds. The retry
    reuses the already-proven platform — no second probe process (and
    its second backend init) between rounds."""
    script = _Script(
        [
            _ok_probe(),
            (None, "tpu worker timed out after 900s "
                   "(last: [bench] round 1/3: 0.9s/epoch)"),
            _tpu_result(),
        ]
    )
    result, errors, cpu_clean = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is not None and result["backend"] == "tpu"
    assert any("timed out" in e for e in errors)
    assert cpu_clean is None
    sides = [s for s, _ in script.calls]
    assert sides == ["preflight", "tpu", "tpu"]
    # the hang marked the run slow-init (annotated, not degraded)
    assert result.get("slow_init") is True


def test_worker_death_without_backend_reprobes_cheaply():
    """A failed round with NO phase evidence of a live backend (the
    tunnel died since it was proven) must fall back to the cheap probe
    — not burn another (widened) full worker window on a dead host."""
    script = _Script(
        [
            _ok_probe(),
            (None, "tpu worker timed out after 900s"),  # no markers
            (None, "preflight worker timed out after 180s"),
            (None, "preflight worker timed out after 360s"),
            (None, "preflight worker timed out after 720s"),
        ]
    )
    result, errors, _ = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is None
    sides = [s for s, _ in script.calls]
    # every retry after the marker-less failure went back to the CHEAP
    # probe; the expensive worker never launched again
    assert sides == [
        "preflight", "tpu", "preflight", "preflight", "preflight"
    ]


def test_worker_timeout_widens_next_window():
    """A timed-out full worker doubles the next round's timeout (the
    slow-platform fall-forward), bounded by the remaining budget."""
    script = _Script(
        [
            _ok_probe(),
            (None, "tpu worker timed out after 900s "
                   "(last: [bench] compile+warmup done in 700.0s)"),
            (None, "tpu worker timed out after 1800s "
                   "(last: [bench] round 1/3: 500.0s/epoch)"),
            _tpu_result(),
        ]
    )
    result, errors, _ = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is not None
    timeouts = [t for s, t in script.calls if s == "tpu"]
    assert timeouts[0] == bench.WORKER_TIMEOUT_S
    assert timeouts[1] > timeouts[0]
    assert all(t <= bench.TOTAL_TPU_BUDGET_S for t in timeouts)


def test_dead_tunnel_fails_fast_in_preflight():
    """A wedged tunnel costs preflight timeouts, never the 900s
    full-workload timeout — and each retry FALLS FORWARD with a wider
    window instead of burning identical short probes."""
    script = _Script(
        [
            (None, "preflight worker timed out after 90s"),
        ]
        * bench.MAX_TPU_ATTEMPTS
    )
    result, errors, _ = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is None
    assert len(errors) == bench.MAX_TPU_ATTEMPTS
    # the expensive full worker never launched
    assert all(side == "preflight" for side, _ in script.calls)
    windows = [t for _, t in script.calls]
    assert windows[0] == bench.PREFLIGHT_TIMEOUT_S
    # widening, monotonic, still inside the total budget
    assert all(b >= a for a, b in zip(windows, windows[1:]))
    assert windows[1] == 2 * bench.PREFLIGHT_TIMEOUT_S
    assert all(w <= bench.TOTAL_TPU_BUDGET_S for w in windows)


def test_slow_preflight_eventually_passes_and_annotates():
    """The r04/r05 regression: a slow-to-init platform must produce a
    REAL TPU number annotated slow_init, not a cpu-fallback record."""
    script = _Script(
        [
            (None, "preflight worker timed out after 180s"),
            _ok_probe(),  # wider window: the platform made it up
            _tpu_result(),
        ]
    )
    result, errors, cpu_clean = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is not None and result["backend"] == "tpu"
    assert result.get("slow_init") is True
    assert cpu_clean is None
    # the second probe got a doubled window
    windows = [t for s, t in script.calls if s == "preflight"]
    assert windows == [
        bench.PREFLIGHT_TIMEOUT_S, 2 * bench.PREFLIGHT_TIMEOUT_S
    ]


def test_non_retryable_error_stops_immediately():
    script = _Script(
        [
            _ok_probe(),
            (None, "ValueError: shapes do not match"),  # a real bug
        ]
    )
    result, errors, _ = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is None
    assert len(errors) == 1
    assert len(script.calls) == 2  # no retry burned on a code bug


def test_cpu_fallback_detected_in_preflight():
    """Plugin silently fell back to cpu: stop, don't fake a TPU number."""
    script = _Script([({"ok": True, "backend": "cpu"}, None)])
    result, errors, cpu_clean = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is None
    assert any("cpu backend" in e for e in errors)
    assert [s for s, _ in script.calls] == ["preflight"]


def test_cpu_fallback_midrun_keeps_measurement():
    script = _Script(
        [
            _ok_probe(),
            ({"seconds": 1.2, "backend": "cpu", "workload": "w"}, None),
        ]
    )
    result, errors, cpu_clean = bench.measure_tpu(
        "default", run_worker=script, sleep=lambda s: None
    )
    assert result is None
    assert cpu_clean is not None and cpu_clean["seconds"] == 1.2


def test_budget_exhaustion_stops_retries():
    clock = {"t": 0.0}

    def monotonic():
        return clock["t"]

    def run_worker(side, scale, timeout):
        clock["t"] += 1000.0  # every call burns past half the budget
        return None, "connection UNAVAILABLE"

    result, errors, _ = bench.measure_tpu(
        "default",
        run_worker=run_worker,
        sleep=lambda s: None,
        monotonic=monotonic,
    )
    assert result is None
    assert errors[-1] == "tpu retry budget exhausted"


def test_retryable_tokens():
    assert bench._retryable("x timed out after 900s")
    assert bench._retryable("backend UNAVAILABLE")
    assert not bench._retryable("AssertionError: wrong answer")
    assert not bench._retryable(None)
