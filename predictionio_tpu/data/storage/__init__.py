"""Env-var-driven storage registry.

Capability parity with the reference's ``Storage`` object
(``data/.../storage/Storage.scala:114-403``): storage *sources* are
declared with ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ per-source config
keys), and the three *repositories* — METADATA, EVENTDATA, MODELDATA —
are bound to sources with
``PIO_STORAGE_REPOSITORIES_<REPO>_{NAME,SOURCE}``.

Where the reference discovers backend classes reflectively by naming
convention (``jdbc.JDBCApps`` etc., Storage.scala:124-193), we use an
explicit registry (:func:`register_backend`) — the idiomatic Python
extension point (SURVEY.md §7 hard-part (e)). Built-ins: ``memory``,
``sqlite``, ``localfs`` (models only).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Mapping

from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeysBackend,
    App,
    AppsBackend,
    Channel,
    ChannelsBackend,
    EngineInstance,
    EngineInstancesBackend,
    EngineManifest,
    EngineManifestsBackend,
    EvaluationInstance,
    EvaluationInstancesBackend,
    EventsBackend,
    Model,
    ModelsBackend,
    StorageError,
)

__all__ = [
    "App", "AccessKey", "Channel", "EngineInstance", "EngineManifest",
    "EvaluationInstance", "Model",
    "AppsBackend", "AccessKeysBackend", "ChannelsBackend",
    "EngineInstancesBackend", "EngineManifestsBackend",
    "EvaluationInstancesBackend", "EventsBackend", "ModelsBackend",
    "Storage", "StorageError", "register_backend", "get_storage",
    "set_storage",
]


@dataclass
class BackendSpec:
    """Factories for one backend type; any entry may be None if the
    backend does not support that repository (reference: hbase = events
    only, elasticsearch = metadata only, localfs = models only)."""

    client: Callable[[dict], object]
    apps: Callable[[object], AppsBackend] | None = None
    access_keys: Callable[[object], AccessKeysBackend] | None = None
    channels: Callable[[object], ChannelsBackend] | None = None
    engine_instances: Callable[[object], EngineInstancesBackend] | None = None
    engine_manifests: Callable[[object], EngineManifestsBackend] | None = None
    evaluation_instances: (
        Callable[[object], EvaluationInstancesBackend] | None
    ) = None
    models: Callable[[object], ModelsBackend] | None = None
    events: Callable[[object], EventsBackend] | None = None


_BACKENDS: dict[str, BackendSpec] = {}


def register_backend(type_name: str, spec: BackendSpec) -> None:
    _BACKENDS[type_name] = spec


def _register_builtins() -> None:
    from predictionio_tpu.data.storage import localfs, memory, sqlite

    class _MemoryClient:
        def __init__(self, config: dict):
            self.apps = memory.MemoryApps()
            self.access_keys = memory.MemoryAccessKeys()
            self.channels = memory.MemoryChannels()
            self.engine_instances = memory.MemoryEngineInstances()
            self.engine_manifests = memory.MemoryEngineManifests()
            self.evaluation_instances = memory.MemoryEvaluationInstances()
            self.models = memory.MemoryModels()
            self.events = memory.MemoryEvents()

    register_backend(
        "memory",
        BackendSpec(
            client=_MemoryClient,
            apps=lambda c: c.apps,
            access_keys=lambda c: c.access_keys,
            channels=lambda c: c.channels,
            engine_instances=lambda c: c.engine_instances,
            engine_manifests=lambda c: c.engine_manifests,
            evaluation_instances=lambda c: c.evaluation_instances,
            models=lambda c: c.models,
            events=lambda c: c.events,
        ),
    )
    register_backend(
        "sqlite",
        BackendSpec(
            client=sqlite.SQLiteClient,
            apps=sqlite.SQLiteApps,
            access_keys=sqlite.SQLiteAccessKeys,
            channels=sqlite.SQLiteChannels,
            engine_instances=sqlite.SQLiteEngineInstances,
            engine_manifests=sqlite.SQLiteEngineManifests,
            evaluation_instances=sqlite.SQLiteEvaluationInstances,
            models=sqlite.SQLiteModels,
            events=sqlite.SQLiteEvents,
        ),
    )
    register_backend(
        "localfs",
        BackendSpec(
            client=lambda config: config,
            models=lambda config: localfs.LocalFSModels(config),
        ),
    )
    # networked production store (reference default: jdbc postgres,
    # Storage.scala "PGSQL" source); the client module imports lazily so
    # registry setup never pays for a driver probe
    def _postgres_client(config: dict):
        from predictionio_tpu.data.storage import postgres

        return postgres.PostgresClient(config)

    from predictionio_tpu.data.storage import sql_common

    def _mysql_client(config: dict):
        from predictionio_tpu.data.storage import mysql

        return mysql.MySQLClient(config)

    _sql_daos = dict(
        apps=sql_common.SQLApps,
        access_keys=sql_common.SQLAccessKeys,
        channels=sql_common.SQLChannels,
        engine_instances=sql_common.SQLEngineInstances,
        engine_manifests=sql_common.SQLEngineManifests,
        evaluation_instances=sql_common.SQLEvaluationInstances,
        models=sql_common.SQLModels,
        events=sql_common.SQLEvents,
    )
    register_backend(
        "postgres", BackendSpec(client=_postgres_client, **_sql_daos)
    )
    register_backend(
        "mysql", BackendSpec(client=_mysql_client, **_sql_daos)
    )
    # networked store server (metadata + models + events, like the
    # reference's elasticsearch + hdfs + hbase backend family); the
    # event routes exist primarily so the replicated tier below can
    # quorum-write and anti-entropy-pull them, but a single remote
    # store server works as a plain event source too
    def _httpstore_client(config: dict):
        from predictionio_tpu.data.storage import httpstore

        return httpstore.HTTPStoreClient(config)

    def _http_dao(name: str):
        def factory(client):
            from predictionio_tpu.data.storage import httpstore

            return getattr(httpstore, name)(client)

        return factory

    register_backend(
        "httpstore",
        BackendSpec(
            client=_httpstore_client,
            apps=_http_dao("HTTPApps"),
            access_keys=_http_dao("HTTPAccessKeys"),
            channels=_http_dao("HTTPChannels"),
            engine_instances=_http_dao("HTTPEngineInstances"),
            engine_manifests=_http_dao("HTTPEngineManifests"),
            evaluation_instances=_http_dao("HTTPEvaluationInstances"),
            models=_http_dao("HTTPModels"),
            events=_http_dao("HTTPEvents"),
        ),
    )
    # replicated tier over N store servers: quorum writes, failover
    # reads with read-repair, hinted handoff (docs/storage.md
    # "Replication & failover"); one client owns the peer pool, every
    # DAO is a fan-out wrapper
    def _replicated_client(config: dict):
        from predictionio_tpu.data.storage import replicated

        return replicated.ReplicatedStoreClient(config)

    def _repl_dao(name: str):
        return lambda client: client.dao(name)

    register_backend(
        "replicated",
        BackendSpec(
            client=_replicated_client,
            apps=_repl_dao("apps"),
            access_keys=_repl_dao("access_keys"),
            channels=_repl_dao("channels"),
            engine_instances=_repl_dao("engine_instances"),
            engine_manifests=_repl_dao("engine_manifests"),
            evaluation_instances=_repl_dao("evaluation_instances"),
            models=_repl_dao("models"),
            events=_repl_dao("events"),
        ),
    )
    # native C++ event log (events only, like the reference's hbase
    # backend); registered lazily — the .so builds on first client use
    from predictionio_tpu.data.storage import eventlog

    register_backend(
        "eventlog",
        BackendSpec(
            client=lambda config: eventlog.EventLogEvents(config),
            events=lambda client: client,
        ),
    )


_register_builtins()

_REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")


class Storage:
    """One configured storage environment.

    Accessors mirror the reference's
    ``Storage.getMetaData*/getLEvents/getModelDataModels``
    (Storage.scala:360-392).
    """

    def __init__(self, env: Mapping[str, str] | None = None):
        self._env = dict(env if env is not None else os.environ)
        self._clients: dict[str, object] = {}
        self._specs: dict[str, tuple[BackendSpec, dict]] = {}
        self._repo_source: dict[str, str] = {}
        self._lock = threading.Lock()
        self._parse()

    # -- env parsing (reference Storage.scala:124-193) --------------------
    def _parse(self) -> None:
        prefix = "PIO_STORAGE_SOURCES_"
        sources: dict[str, dict] = {}
        for k, v in self._env.items():
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            name, _, key = rest.partition("_")
            sources.setdefault(name, {})[key] = v
        for name, conf in sources.items():
            type_name = conf.get("TYPE")
            if type_name is None:
                continue
            spec = _BACKENDS.get(type_name)
            if spec is None:
                raise StorageError(
                    f"storage source {name}: unknown backend type "
                    f"{type_name!r} (registered: {sorted(_BACKENDS)})"
                )
            self._specs[name] = (spec, conf)

        for repo in _REPOSITORIES:
            src = self._env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
            if src is not None:
                if src not in self._specs:
                    raise StorageError(
                        f"repository {repo} bound to undeclared source {src}"
                    )
                self._repo_source[repo] = src

        if not self._specs:
            self._default_wiring()

    def _default_wiring(self) -> None:
        """Zero-config default: sqlite for metadata+events, localfs models
        under ``PIO_FS_BASEDIR`` (default ``~/.piotpu``)."""
        base = self._env.get(
            "PIO_FS_BASEDIR",
            os.path.join(os.path.expanduser("~"), ".piotpu"),
        )
        self._specs = {
            "SQLITE": (
                _BACKENDS["sqlite"],
                {"TYPE": "sqlite", "PATH": os.path.join(base, "pio.sqlite")},
            ),
            "LOCALFS": (
                _BACKENDS["localfs"],
                {"TYPE": "localfs", "PATH": os.path.join(base, "models")},
            ),
        }
        self._repo_source = {
            "METADATA": "SQLITE",
            "EVENTDATA": "SQLITE",
            "MODELDATA": "LOCALFS",
        }

    def _client(self, source: str):
        with self._lock:
            if source not in self._clients:
                spec, conf = self._specs[source]
                self._clients[source] = spec.client(conf)
            return self._clients[source]

    def _dao(self, repo: str, attr: str):
        source = self._repo_source.get(repo)
        if source is None:
            if len(self._specs) == 1:
                # exactly one declared source: binding is unambiguous
                source = next(iter(self._specs))
            else:
                raise StorageError(
                    f"repository {repo} is not bound to a source; set "
                    f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE to one of "
                    f"{sorted(self._specs)}"
                )
        spec, _conf = self._specs[source]
        factory = getattr(spec, attr)
        if factory is None:
            raise StorageError(
                f"storage source {source} does not support {attr} "
                f"(repository {repo})"
            )
        return factory(self._client(source))

    # -- accessors --------------------------------------------------------
    def get_meta_data_apps(self) -> AppsBackend:
        return self._dao("METADATA", "apps")

    def get_meta_data_access_keys(self) -> AccessKeysBackend:
        return self._dao("METADATA", "access_keys")

    def get_meta_data_channels(self) -> ChannelsBackend:
        return self._dao("METADATA", "channels")

    def get_meta_data_engine_instances(self) -> EngineInstancesBackend:
        return self._dao("METADATA", "engine_instances")

    def get_meta_data_engine_manifests(self) -> EngineManifestsBackend:
        return self._dao("METADATA", "engine_manifests")

    def get_meta_data_evaluation_instances(
        self,
    ) -> EvaluationInstancesBackend:
        return self._dao("METADATA", "evaluation_instances")

    def get_model_data_models(self) -> ModelsBackend:
        return self._dao("MODELDATA", "models")

    def get_events(self) -> EventsBackend:
        return self._dao("EVENTDATA", "events")

    def backend_for_source(self, source: str) -> EventsBackend:
        """Events backend of a *specific* declared source, regardless of
        repository bindings — used by ``pio-tpu upgrade`` migration."""
        if source not in self._specs:
            raise StorageError(
                f"unknown storage source {source}; declared: "
                f"{sorted(self._specs)}"
            )
        spec, _conf = self._specs[source]
        if spec.events is None:
            raise StorageError(
                f"storage source {source} does not support events"
            )
        return spec.events(self._client(source))

    # -- health (reference Storage.verifyAllDataObjects:335-358) ----------
    def verify_all_data_objects(self) -> list[str]:
        """Instantiate every DAO + event-store write/remove roundtrip on
        app id 0; returns a list of problems (empty = healthy)."""
        problems: list[str] = []
        for name in (
            "get_meta_data_apps",
            "get_meta_data_access_keys",
            "get_meta_data_channels",
            "get_meta_data_engine_instances",
            "get_meta_data_engine_manifests",
            "get_meta_data_evaluation_instances",
            "get_model_data_models",
        ):
            try:
                getattr(self, name)()
            except Exception as e:  # noqa: BLE001 - health check surface
                problems.append(f"{name}: {e}")
        try:
            events = self.get_events()
            events.init(0)
            from predictionio_tpu.data.event import Event

            eid = events.insert(
                Event(event="$set", entity_type="health", entity_id="0"),
                0,
            )
            events.delete(eid, 0)
            events.remove(0)
        except Exception as e:  # noqa: BLE001
            problems.append(f"events: {e}")
        return problems


_default_storage: Storage | None = None
_default_lock = threading.Lock()


def get_storage() -> Storage:
    """Process-default storage parsed from ``os.environ``."""
    global _default_storage
    with _default_lock:
        if _default_storage is None:
            _default_storage = Storage()
        return _default_storage


def set_storage(storage: Storage | None) -> None:
    """Override the process default (tests, embedded use)."""
    global _default_storage
    with _default_lock:
        _default_storage = storage
