"""Serving query cache: canonicalization, byte-budgeted LRU,
generation keying, single-flight coalescing, flush semantics, env
knobs, and the shared Zipf key generator the skew bench draws from."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.obs import timeline as timeline_mod
from predictionio_tpu.serving import admission
from predictionio_tpu.serving import querycache
from predictionio_tpu.serving.querycache import (
    LeaderFailed,
    QueryCache,
    WaiterTimeout,
    canonical_query_bytes,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


GEN = "inst-1:0"


def _fill(cache, tenant, gen, query, value: bytes):
    claim = cache.claim(tenant, gen, canonical_query_bytes(query))
    assert claim.leader
    cache.fill(claim, value)
    return claim


class TestCanonicalization:
    def test_key_order_invariant(self):
        assert canonical_query_bytes(
            {"b": 2, "a": 1}
        ) == canonical_query_bytes({"a": 1, "b": 2})

    def test_volatile_fields_stripped(self):
        assert canonical_query_bytes(
            {"x": 1, "prId": "abc", "pid": 42, "generation": "g9"}
        ) == canonical_query_bytes({"x": 1})

    def test_distinct_queries_distinct_keys(self):
        assert canonical_query_bytes({"x": 1}) != canonical_query_bytes(
            {"x": 2}
        )

    def test_compact_and_deterministic(self):
        canon = canonical_query_bytes({"user": "u1", "num": 3})
        assert canon == b'{"num":3,"user":"u1"}'


class TestLRU:
    def test_hit_after_fill(self):
        cache = QueryCache(1 << 20, shards=2)
        _fill(cache, "", GEN, {"x": 1}, b"answer")
        claim = cache.claim("", GEN, canonical_query_bytes({"x": 1}))
        assert claim.hit and claim.value == b"answer"

    def test_generation_key_misses_across_swap(self):
        cache = QueryCache(1 << 20, shards=2)
        _fill(cache, "", "inst-1:0", {"x": 1}, b"old")
        claim = cache.claim(
            "", "inst-2:1", canonical_query_bytes({"x": 1})
        )
        assert not claim.hit and claim.leader

    def test_tenants_are_isolated(self):
        cache = QueryCache(1 << 20, shards=2)
        _fill(cache, "t1", GEN, {"x": 1}, b"t1-answer")
        claim = cache.claim("t2", GEN, canonical_query_bytes({"x": 1}))
        assert not claim.hit

    def test_budget_evicts_lru_first(self):
        # one shard so LRU order is global; entries ~(5 + canon + 256)
        cache = QueryCache(1200, shards=1)
        for i in range(4):
            _fill(cache, "", GEN, {"x": i}, b"v" * 5)
        # 4 entries at ~270 B exceed 1200 only at the 5th; touch x=0
        # so x=1 is the LRU victim when overflow comes
        assert cache.claim(
            "", GEN, canonical_query_bytes({"x": 0})
        ).hit
        _fill(cache, "", GEN, {"x": 99}, b"v" * 5)
        assert cache.resident_bytes() <= 1200
        assert cache.claim(
            "", GEN, canonical_query_bytes({"x": 0})
        ).hit, "recently-touched entry survived"
        assert not cache.claim(
            "", GEN, canonical_query_bytes({"x": 1})
        ).hit, "LRU entry evicted"

    def test_oversized_entry_never_inserted(self):
        cache = QueryCache(512, shards=1)
        _fill(cache, "", GEN, {"x": 1}, b"v" * 4096)
        assert len(cache) == 0
        assert cache.resident_bytes() == 0

    def test_eviction_counter_and_pressure_event(self):
        registry = MetricRegistry()
        timeline = timeline_mod.Timeline()
        cache = QueryCache(
            600, shards=1, registry=registry, timeline=timeline,
            pressure_burst=3, pressure_window_s=60.0,
        )
        for i in range(8):
            _fill(cache, "", GEN, {"x": i}, b"v" * 10)
        evicted = sum(
            s["value"]
            for s in registry.to_dict()["pio_cache_evictions_total"][
                "samples"
            ]
        )
        assert evicted >= 3
        kinds = [e["kind"] for e in timeline.to_dict()["events"]]
        assert "cache_pressure" in kinds

    def test_ttl_expiry(self):
        now = [0.0]
        cache = QueryCache(
            1 << 20, shards=1, ttl_s=5.0, clock=lambda: now[0]
        )
        _fill(cache, "", GEN, {"x": 1}, b"answer")
        assert cache.claim(
            "", GEN, canonical_query_bytes({"x": 1})
        ).hit
        now[0] = 6.0
        claim = cache.claim("", GEN, canonical_query_bytes({"x": 1}))
        assert not claim.hit and claim.leader

    def test_stats_shape(self):
        cache = QueryCache(4096, shards=2, ttl_s=9.0)
        _fill(cache, "", GEN, {"x": 1}, b"answer")
        stats = cache.stats()
        assert stats["budgetBytes"] == 4096
        assert stats["entries"] == 1
        assert stats["residentBytes"] == cache.resident_bytes() > 0
        assert stats["shards"] == 2
        assert stats["ttlS"] == 9.0
        assert stats["inflight"] == 0


class TestSingleFlight:
    def test_concurrent_identical_misses_one_leader(self):
        """N concurrent identical cold lookups -> exactly ONE compute
        (the call-count proof): every other claim coalesces and gets
        the leader's bytes."""
        cache = QueryCache(1 << 20, shards=2)
        canon = canonical_query_bytes({"x": 1})
        compute_calls = []
        results: list[bytes] = []
        errors: list[BaseException] = []
        barrier = threading.Barrier(8)
        go = threading.Event()

        def one():
            barrier.wait()
            claim = cache.claim("", GEN, canon)
            if claim.hit:
                results.append(claim.value)
                return
            if claim.leader:
                go.wait(5)  # hold leadership until all claims landed
                compute_calls.append(1)
                cache.fill(claim, b"computed")
                results.append(b"computed")
                return
            try:
                results.append(cache.join(claim, timeout_s=5.0))
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=one, daemon=True) for _ in range(8)
        ]
        for t in threads:
            t.start()
        # release the leader once every thread has claimed
        deadline = time.monotonic() + 5
        while cache.stats()["waiters"] < 7:
            assert time.monotonic() < deadline, cache.stats()
            time.sleep(0.005)
        go.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors
        assert len(compute_calls) == 1, "single-flight dispatched twice"
        assert results == [b"computed"] * 8

    def test_waiter_own_deadline_detaches(self):
        cache = QueryCache(1 << 20, shards=1)
        canon = canonical_query_bytes({"x": 1})
        leader = cache.claim("", GEN, canon)
        assert leader.leader
        waiter = cache.claim("", GEN, canon)
        assert not waiter.leader and not waiter.hit
        t0 = time.monotonic()
        with pytest.raises(WaiterTimeout):
            cache.join(waiter, timeout_s=0.05)
        assert time.monotonic() - t0 < 2.0
        # the leader is untouched: its fill still lands + is cached
        cache.fill(leader, b"late")
        assert cache.claim("", GEN, canon).value == b"late"

    def test_leader_failure_propagates_without_poisoning(self):
        cache = QueryCache(1 << 20, shards=1)
        canon = canonical_query_bytes({"x": 1})
        leader = cache.claim("", GEN, canon)
        waiter = cache.claim("", GEN, canon)
        boom = ValueError("model exploded")
        cache.abort(leader, boom)
        with pytest.raises(LeaderFailed) as excinfo:
            cache.join(waiter, timeout_s=1.0)
        assert excinfo.value.__cause__ is boom
        # no negative caching: the next claimant leads afresh
        fresh = cache.claim("", GEN, canon)
        assert fresh.leader and not fresh.hit
        cache.fill(fresh, b"recovered")
        assert cache.claim("", GEN, canon).hit

    def test_criticality_escalates_to_highest_waiter(self):
        cache = QueryCache(1 << 20, shards=1)
        canon = canonical_query_bytes({"x": 1})
        with admission.criticality(admission.SHEDDABLE):
            leader = cache.claim("", GEN, canon)
        assert leader.criticality() == admission.SHEDDABLE
        with admission.criticality(admission.CRITICAL):
            cache.claim("", GEN, canon)
        assert leader.criticality() == admission.CRITICAL

    def test_coalesced_counter(self):
        registry = MetricRegistry()
        cache = QueryCache(1 << 20, shards=1, registry=registry)
        canon = canonical_query_bytes({"x": 1})
        leader = cache.claim("", GEN, canon)
        cache.claim("", GEN, canon)
        cache.fill(leader, b"v")
        data = registry.to_dict()
        assert data["pio_cache_misses_total"]["samples"][0]["value"] == 1
        assert (
            data["pio_cache_coalesced_total"]["samples"][0]["value"] == 1
        )


class TestFlush:
    def test_flush_drops_and_records_event(self):
        timeline = timeline_mod.Timeline()
        cache = QueryCache(1 << 20, shards=2, timeline=timeline)
        _fill(cache, "", GEN, {"x": 1}, b"a")
        _fill(cache, "", GEN, {"x": 2}, b"b")
        dropped = cache.flush(reason="reload", generation="inst-2")
        assert dropped == 2 and len(cache) == 0
        events = [
            e for e in timeline.to_dict()["events"]
            if e["kind"] == "cache_flush"
        ]
        assert events and events[-1]["reason"] == "reload"
        assert events[-1]["dropped"] == 2

    def test_tenant_scoped_flush(self):
        cache = QueryCache(1 << 20, shards=2)
        _fill(cache, "t1", GEN, {"x": 1}, b"a")
        _fill(cache, "t2", GEN, {"x": 1}, b"b")
        cache.flush("t1", reason="reload")
        assert not cache.claim(
            "t1", GEN, canonical_query_bytes({"x": 1})
        ).hit
        assert cache.claim(
            "t2", GEN, canonical_query_bytes({"x": 1})
        ).hit

    def test_post_flush_fill_not_resurrected(self):
        """A fill whose claim predates the flush must not re-insert the
        entry the flush was meant to kill — but its waiters still get
        the computed bytes."""
        cache = QueryCache(1 << 20, shards=1)
        canon = canonical_query_bytes({"x": 1})
        leader = cache.claim("", GEN, canon)
        waiter = cache.claim("", GEN, canon)
        cache.flush(reason="promote")
        cache.fill(leader, b"stale-gen-answer")
        assert cache.join(waiter, timeout_s=1.0) == b"stale-gen-answer"
        assert len(cache) == 0, "flushed claim resurrected an entry"

    def test_close_fails_waiters(self):
        cache = QueryCache(1 << 20, shards=1)
        canon = canonical_query_bytes({"x": 1})
        cache.claim("", GEN, canon)  # leader, never fills
        waiter = cache.claim("", GEN, canon)
        cache.close()
        with pytest.raises(LeaderFailed):
            cache.join(waiter, timeout_s=1.0)


class TestEnvKnobs:
    def test_enabled_flag(self, monkeypatch):
        monkeypatch.delenv("PIO_CACHE", raising=False)
        monkeypatch.delenv("PIO_CACHE_BUDGET_BYTES", raising=False)
        assert not querycache.cache_enabled_from_env()
        monkeypatch.setenv("PIO_CACHE", "1")
        assert querycache.cache_enabled_from_env()
        monkeypatch.setenv("PIO_CACHE", "off")
        monkeypatch.setenv("PIO_CACHE_BUDGET_BYTES", "1024")
        assert not querycache.cache_enabled_from_env(), (
            "explicit PIO_CACHE=off must win over a budget"
        )
        monkeypatch.delenv("PIO_CACHE")
        assert querycache.cache_enabled_from_env(), (
            "a budget alone opts in"
        )

    def test_budget_malformed_falls_back(self, monkeypatch):
        monkeypatch.setenv("PIO_CACHE_BUDGET_BYTES", "not-a-number")
        assert querycache.default_budget_bytes() == 64 << 20
        monkeypatch.setenv("PIO_CACHE_BUDGET_BYTES", "-5")
        assert querycache.default_budget_bytes() == 64 << 20
        monkeypatch.setenv("PIO_CACHE_BUDGET_BYTES", "4096")
        assert querycache.default_budget_bytes() == 4096

    def test_shards_and_ttl_from_env(self, monkeypatch):
        monkeypatch.setenv("PIO_CACHE_SHARDS", "3")
        monkeypatch.setenv("PIO_CACHE_TTL_S", "2.5")
        cache = QueryCache(1 << 20)
        assert cache.stats()["shards"] == 3
        assert cache.stats()["ttlS"] == 2.5
        monkeypatch.setenv("PIO_CACHE_SHARDS", "zero")
        monkeypatch.setenv("PIO_CACHE_TTL_S", "-1")
        cache = QueryCache(1 << 20)
        assert cache.stats()["shards"] == 8
        assert cache.stats()["ttlS"] is None


class TestBenchKeys:
    """The shared Zipf generator both serving_bench modes draw from."""

    def test_seeded_deterministic(self):
        import bench_keys

        a = bench_keys.zipf_sequence(100, 500, alpha=1.1, seed=7)
        b = bench_keys.zipf_sequence(100, 500, alpha=1.1, seed=7)
        assert np.array_equal(a, b)
        c = bench_keys.zipf_sequence(100, 500, alpha=1.1, seed=8)
        assert not np.array_equal(a, c)

    def test_alpha_one_matches_legacy_density_weights(self):
        """--density always used 1/rank; alpha=1.0 must be bit-equal so
        extracting the shared generator changed no density draws."""
        import bench_keys

        legacy = 1.0 / (1.0 + np.arange(50))
        legacy = legacy / legacy.sum()
        assert np.array_equal(bench_keys.zipf_weights(50, 1.0), legacy)

    def test_alpha_zero_is_uniform(self):
        import bench_keys

        w = bench_keys.zipf_weights(10, 0.0)
        assert np.allclose(w, 0.1)

    def test_higher_alpha_concentrates_head(self):
        import bench_keys

        w09 = bench_keys.zipf_weights(1000, 0.9)
        w11 = bench_keys.zipf_weights(1000, 1.1)
        assert w11[0] > w09[0]
        assert w11[-1] < w09[-1]

    def test_bounds_and_validation(self):
        import bench_keys

        seq = bench_keys.zipf_sequence(10, 200, alpha=1.1, seed=0)
        assert seq.min() >= 0 and seq.max() < 10
        with pytest.raises(ValueError):
            bench_keys.zipf_weights(0)


def test_volatile_keys_match_canary_scorer():
    """The cache strips exactly the fields the canary's divergence
    scorer ignores — one volatile set, no drift."""
    from predictionio_tpu.serving import canary

    stripped = json.loads(
        canonical_query_bytes(
            {k: 1 for k in canary.VOLATILE_PREDICTION_KEYS} | {"x": 2}
        )
    )
    assert stripped == {"x": 2}
