"""Recommendation template — implicit/explicit ALS.

Capability parity with the reference
``examples/scala-parallel-recommendation`` (custom-query variant:
MLlib ``ALS.trainImplicit`` over "rate" events,
custom-query/src/main/scala/ALSAlgorithm.scala:24-105,
DataSource.scala:23-66): events (user → item with a rating property)
train factor matrices; queries ``{"user": id, "num": N}`` answer
``{"itemScores": [{"item": id, "score": s}, ...]}``.

TPU path: mesh ALS (:func:`predictionio_tpu.ops.als.train_als`) for
training; serving scores with one pre-compiled matmul + top-k instead of
the reference's per-query Spark job.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import numpy as np

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    register_engine,
)
from predictionio_tpu.core.controller import SanityCheck
from predictionio_tpu.data.eventframe import Interactions
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.ops import similarity
from predictionio_tpu.ops.als import train_als
from predictionio_tpu.parallel import partition
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.utils.bimap import BiMap

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RecDataSourceParams(Params):
    app_name: str = "MyApp"
    event_names: tuple[str, ...] = ("rate",)
    rating_key: str | None = "rating"  # None → implicit count of 1 per event
    eval_k: int = 0


@dataclasses.dataclass
class RecTrainingData(SanityCheck):
    interactions: Interactions

    def sanity_check(self) -> None:
        if self.interactions.nnz == 0:
            raise ValueError("no interaction events found")


class RecDataSource(DataSource[RecTrainingData, dict, dict, list]):
    params_class = RecDataSourceParams

    def _interactions(self) -> Interactions:
        p = self.params
        # uses the backend's native columnar scan when available
        return EventStore().interactions(
            p.app_name,
            event_names=list(p.event_names),
            value_key=p.rating_key,
        )

    def read_training(self, ctx: ComputeContext) -> RecTrainingData:
        return RecTrainingData(interactions=self._interactions())

    def read_eval(self, ctx: ComputeContext):
        """k-fold over interactions (shared
        :func:`~predictionio_tpu.core.evaluation.kfold_indices`):
        held-out items per user become the actuals (ranking
        evaluation)."""
        from predictionio_tpu.core.evaluation import kfold_indices

        inter = self._interactions()
        folds = []
        for fold, train_idx, test_idx in kfold_indices(
            inter.nnz, self.params.eval_k
        ):
            train = Interactions(
                entity_map=inter.entity_map,
                target_map=inter.target_map,
                rows=inter.rows[train_idx],
                cols=inter.cols[train_idx],
                values=inter.values[train_idx],
                times=inter.times[train_idx],
            )
            # group held-out items by user
            by_user: dict[int, list[str]] = {}
            for r, c in zip(inter.rows[test_idx], inter.cols[test_idx]):
                by_user.setdefault(int(r), []).append(
                    inter.target_map.inverse(int(c))
                )
            qa = [
                (
                    {
                        "user": inter.entity_map.inverse(u),
                        "num": max(10, len(items)),
                    },
                    items,
                )
                for u, items in by_user.items()
            ]
            folds.append(
                (RecTrainingData(interactions=train), {"fold": fold}, qa)
            )
        return folds


@dataclasses.dataclass(frozen=True)
class RecPreparatorParams(Params):
    dedupe: str = "sum"  # "sum" (implicit counts) | "latest" (ratings)


class RecPreparator(Preparator[RecTrainingData, RecTrainingData]):
    """Dedupe repeated (user, item) events — MLlib-convention sum for
    implicit counts, keep-latest for rating data (reference DataSource
    takes the latest "rate" event per pair)."""

    params_class = RecPreparatorParams

    def prepare(
        self, ctx: ComputeContext, td: RecTrainingData
    ) -> RecTrainingData:
        inter = td.interactions
        deduped = (
            inter.dedupe_latest()
            if self.params.dedupe == "latest"
            else inter.dedupe_sum()
        )
        return RecTrainingData(interactions=deduped)


@dataclasses.dataclass(frozen=True)
class ALSParams(Params):
    """Reference ALSAlgorithmParams (rank, numIterations, lambda, seed,
    custom-query/src/main/scala/ALSAlgorithm.scala:19-22) + implicit
    controls."""

    rank: int = 32
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    implicit: bool = True
    seed: int = 13
    block_len: int = 64
    row_chunk: int = 256
    #: "" = auto (bf16 on TPU, f32 elsewhere — quality A/B in
    #: BASELINE.md); "float32" opts out, "bfloat16" forces bf16
    compute_dtype: str = ""
    # mid-training checkpoint/resume (ops/als.py); dir empty = disabled
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    resume: bool = False
    #: factor-matrix layout: "auto" shards over the model mesh axis
    #: whenever the serving/training mesh has one (docs/parallelism.md
    #: "Sharded ALS"); "replicated"/"sharded" force a mode
    factor_sharding: str = "auto"


@dataclasses.dataclass
class ALSRecModel:
    # np.ndarray after train (host, picklable); device-committed
    # jax.Array after Algorithm.stage_model at deploy
    user_factors: np.ndarray | jax.Array
    item_factors: np.ndarray | jax.Array
    user_map: BiMap
    item_map: BiMap
    #: [rows(item_factors)] bool device array, True on phantom padding
    #: rows of a model-sharded catalog (None when factors are
    #: unpadded); serving passes it as the top-k score mask so a
    #: padded row never surfaces as a recommendation. Optional so
    #: pre-sharding pickled models load unchanged.
    item_phantom_mask: "jax.Array | None" = None


class ALSAlgorithm(Algorithm[RecTrainingData, ALSRecModel, dict, dict]):
    params_class = ALSParams

    def train(self, ctx: ComputeContext, pd: RecTrainingData) -> ALSRecModel:
        p = self.params
        inter = pd.interactions
        factors = train_als(
            ctx,
            inter.rows,
            inter.cols,
            inter.values,
            n_users=inter.n_rows,
            n_items=inter.n_cols,
            rank=p.rank,
            iterations=p.num_iterations,
            reg=p.lambda_,
            alpha=p.alpha,
            implicit=p.implicit,
            seed=p.seed,
            block_len=p.block_len,
            row_chunk=p.row_chunk,
            compute_dtype=p.compute_dtype or None,
            timer=self.timer,
            checkpoint_dir=p.checkpoint_dir or None,
            checkpoint_every=p.checkpoint_every,
            resume=p.resume,
            factor_sharding=p.factor_sharding,
        )
        return ALSRecModel(
            user_factors=factors.user_factors,
            item_factors=factors.item_factors,
            user_map=inter.entity_map,
            item_map=inter.target_map,
        )

    # -- serving ----------------------------------------------------------
    def stage_model(
        self, ctx: ComputeContext, model: ALSRecModel
    ) -> ALSRecModel:
        """Commit both factor matrices once at deploy; the per-request
        upload is then just the int32 user indices.

        On a mesh with a model axis the matrices are committed
        ROW-SHARDED over it (the same partition rule that trained
        them), so the catalog's HBM footprint divides by
        model_parallelism — a factor table too big for one chip serves
        from one engine instance; on a model-axis-1 mesh the same spec
        is physically replicated. Already-sharded device arrays (the
        ``train_als(return_layout="device")`` path) pass straight
        through without a host gather. The phantom mask is keyed on
        the factors actually carrying padded rows (device-layout
        training pads on EVERY mesh, data-parallel ones included) —
        never on the mesh shape."""
        user_f, _ = partition.stage_factor_matrix(
            ctx, model.user_factors, n_real=len(model.user_map)
        )
        item_f, item_mask = partition.stage_factor_matrix(
            ctx, model.item_factors, n_real=len(model.item_map)
        )
        return dataclasses.replace(
            model,
            user_factors=user_f,
            item_factors=item_f,
            item_phantom_mask=item_mask,
        )

    def predict(self, model: ALSRecModel, query: dict) -> dict:
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: ALSRecModel, queries) -> list[dict]:
        if not queries:
            return []
        return self.batch_predict_collect(
            model, self.batch_predict_launch(model, queries), queries
        )

    def batch_predict_launch(self, model: ALSRecModel, queries):
        """Host prep + device enqueue, no barrier: the returned handle
        holds un-fetched device arrays, so the serving pipeline can
        enqueue the next batch while this one computes. Works unchanged
        on model-sharded factor matrices (the jitted program runs GSPMD
        over their mesh; nothing here gathers factors to the host) —
        phantom padding rows are masked out of the ranking and the
        top-k size clamps to the REAL catalog, never the padded one."""
        if not queries:
            return None
        n_items = len(model.item_map)
        num = max(int(q.get("num", 10)) for q in queries)
        num = min(num, n_items)
        # bucket the jit-static shapes (top-k size and batch rows) to
        # powers of two so arbitrary client input cannot force unbounded
        # recompiles at serving time
        num_bucket = min(1 << max(0, (num - 1)).bit_length(), n_items)
        user_idx = np.asarray(
            [model.user_map.get(q.get("user", ""), -1) for q in queries],
            np.int32,
        )
        idx = np.clip(user_idx, 0, None)
        batch_bucket = 1 << max(0, (len(idx) - 1)).bit_length()
        if batch_bucket > len(idx):
            idx = np.pad(idx, (0, batch_bucket - len(idx)))
        # fused gather + score + top-k on device: uploads only `idx`
        # (factors are staged jax.Arrays after stage_model; the
        # evaluation path passes host arrays and pays the upload there)
        scores, items = similarity.gather_top_k_dot(
            model.user_factors, idx, model.item_factors, num_bucket,
            mask=getattr(model, "item_phantom_mask", None),
        )
        return scores, items, user_idx, num

    def batch_predict_collect(
        self, model: ALSRecModel, handle, queries
    ) -> list[dict]:
        """Device barrier + per-query JSON materialization for a
        :meth:`batch_predict_launch` handle."""
        if handle is None:
            return []
        scores, items, user_idx, num = handle
        # one parallel device_get: through remote-TPU transports each
        # separate fetch pays a full round trip (~70 ms on the tunnel)
        scores, items = jax.device_get((scores, items))
        out = []
        for i, q in enumerate(queries):
            if user_idx[i] < 0:
                out.append({"itemScores": []})  # unknown user
                continue
            n = min(int(q.get("num", 10)), num)
            out.append(
                {
                    "itemScores": [
                        {
                            "item": model.item_map.inverse(int(items[i, j])),
                            "score": float(scores[i, j]),
                        }
                        for j in range(n)
                    ]
                }
            )
        return out


def recommendation_engine() -> Engine:
    return Engine(
        RecDataSource,
        RecPreparator,
        {"als": ALSAlgorithm},
        FirstServing,
    )


register_engine("recommendation", recommendation_engine)
