"""Partition engine (parallel/partition.py): regex rule matching,
axis validation, rule-driven staging, topology helpers, and the
serving-side sharded-factor staging with its phantom mask."""

from __future__ import annotations

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import get_registry
from predictionio_tpu.parallel import partition
from predictionio_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    ComputeContext,
    assert_phantom_rows_zero,
)


@pytest.fixture(scope="module")
def ctx42():
    return ComputeContext.create(batch="pt-2d", mesh_shape=(4, 2))


@pytest.fixture(scope="module")
def ctx8():
    return ComputeContext.create(batch="pt-1d", mesh_shape=(8, 1))


class TestMatchPartitionRules:
    RULES = (
        (r"(^|/)(user|item)_factors$", P(MODEL_AXIS, None)),
        (r"(^|/)idx$", P(DATA_AXIS)),
        (r".*", P()),
    )

    def test_first_matching_rule_wins(self):
        rules = (
            (r"factors", P(MODEL_AXIS, None)),
            (r"item_factors", P(DATA_AXIS, None)),
        )
        spec = partition.match_partition_rule(rules, "item_factors")
        assert spec == P(MODEL_AXIS, None)

    def test_tree_paths_drive_matching(self):
        tree = {
            "user_factors": np.zeros((8, 4)),
            "slabs": [{"idx": np.zeros((8, 2), np.int32)}],
            "other": np.zeros((4, 4)),
        }
        specs = partition.match_partition_rules(self.RULES, tree)
        assert specs["user_factors"] == P(MODEL_AXIS, None)
        assert specs["slabs"][0]["idx"] == P(DATA_AXIS)
        assert specs["other"] == P()

    def test_scalar_leaves_never_partitioned(self):
        tree = {"user_factors": np.float32(3.0), "idx": np.zeros((1,))}
        specs = partition.match_partition_rules(self.RULES, tree)
        # both scalar-like: the factors rule is never consulted
        assert specs["user_factors"] == P()
        assert specs["idx"] == P()

    def test_unmatched_leaf_raises(self):
        rules = ((r"^only_this$", P()),)
        with pytest.raises(ValueError, match="no partition rule"):
            partition.match_partition_rules(
                rules, {"something_else": np.zeros((4, 4))}
            )

    def test_leaf_names(self):
        tree = {"a": [np.zeros(2), {"b": np.zeros(2)}]}
        names = partition.tree_leaf_names(tree)
        assert names == ["a/0", "a/1/b"]


class TestValidateRules:
    def test_bad_axis_raises_with_rule_named(self, ctx42):
        rules = ((r"x", P("modle")),)  # typo'd axis
        with pytest.raises(ValueError, match="modle"):
            partition.validate_rules(rules, ctx42.mesh)

    def test_bad_axis_inside_tuple_entry(self, ctx42):
        rules = ((r"x", P((DATA_AXIS, "replica"), None)),)
        with pytest.raises(ValueError, match="replica"):
            partition.validate_rules(rules, ctx42.mesh)

    def test_known_axes_pass(self, ctx42):
        partition.validate_rules(partition.ALS_SHARDED_RULES, ctx42.mesh)
        partition.validate_rules(
            partition.ALS_REPLICATED_RULES, ctx42.mesh
        )

    def test_shard_pytree_validates_by_default(self, ctx42):
        with pytest.raises(ValueError, match="ghost"):
            partition.shard_pytree(
                ctx42, ((r".*", P("ghost")),), {"x": np.zeros((8, 2))}
            )


class TestShardPytree:
    def test_als_sharded_placements(self, ctx42):
        tree = {
            "user_factors": np.zeros((16, 4), np.float32),
            "slabs": [
                {
                    "idx": np.zeros((8, 4), np.int32),
                    "weights": np.zeros((8, 4), np.float32),
                    "valid": np.zeros((8, 4), np.float32),
                }
            ],
            "heavy": {"owner": np.zeros(8, np.int32)},
            "inv_perm": np.arange(16, dtype=np.int32),
        }
        placed = partition.shard_pytree(
            ctx42, partition.ALS_SHARDED_RULES, tree
        )
        mesh = ctx42.mesh
        assert placed["user_factors"].sharding == NamedSharding(
            mesh, P(MODEL_AXIS, None)
        )
        assert placed["slabs"][0]["idx"].sharding == NamedSharding(
            mesh, P((DATA_AXIS, MODEL_AXIS), None)
        )
        assert placed["heavy"]["owner"].sharding == NamedSharding(
            mesh, P((DATA_AXIS, MODEL_AXIS))
        )
        assert placed["inv_perm"].sharding == NamedSharding(
            mesh, P(MODEL_AXIS)
        )

    def test_replicated_placements(self, ctx8):
        placed = partition.shard_pytree(
            ctx8,
            partition.ALS_REPLICATED_RULES,
            {
                "user_factors": np.zeros((16, 4), np.float32),
                "idx": np.zeros((8, 4), np.int32),
            },
        )
        assert placed["user_factors"].sharding.spec == P()
        assert placed["idx"].sharding.spec == P(DATA_AXIS)


class TestTopology:
    def test_default_even_gets_model_axis(self):
        assert partition.topology_mesh_shape(8) == (4, 2)
        assert partition.topology_mesh_shape(2) == (1, 2)

    def test_one_device_degenerates(self):
        assert partition.topology_mesh_shape(1) == (1, 1)

    def test_odd_count_pure_data(self):
        assert partition.topology_mesh_shape(3) == (3, 1)

    def test_explicit_model_parallelism(self):
        assert partition.topology_mesh_shape(8, 4) == (2, 4)

    def test_non_dividing_model_axis_rejected(self):
        with pytest.raises(ValueError, match="does not divide"):
            partition.topology_mesh_shape(8, 3)

    def test_mesh_from_topology_counts(self):
        ctx = partition.mesh_from_topology(4, batch="pt-topo")
        assert ctx.n_devices == 4
        assert ctx.model_parallelism == 2
        with pytest.raises(ValueError, match="have"):
            partition.mesh_from_topology(99)


class TestShardMapCompat:
    def test_shim_runs_on_this_jax(self, ctx42):
        """The version-portable shard_map executes a trivial body —
        guards the 0.4.x (check_rep) vs newer (check_vma) seam that
        kept the whole sharded block in known_failures."""
        import jax.numpy as jnp

        def body(x):
            return x * 2

        f = jax.jit(
            partition.shard_map(
                body,
                mesh=ctx42.mesh,
                in_specs=(P(MODEL_AXIS, None),),
                out_specs=P(MODEL_AXIS, None),
            )
        )
        x = jax.device_put(
            np.ones((8, 2), np.float32),
            NamedSharding(ctx42.mesh, P(MODEL_AXIS, None)),
        )
        np.testing.assert_allclose(np.asarray(f(x)), 2.0)
        assert isinstance(f(x), jax.Array)
        del jnp


class TestStageFactorMatrix:
    def test_pads_and_masks(self, ctx42):
        arr = np.random.default_rng(0).normal(size=(9, 4)).astype(
            np.float32
        )
        staged, mask = partition.stage_factor_matrix(ctx42, arr, n_real=9)
        assert staged.shape == (10, 4)  # padded to model multiple (2)
        assert staged.sharding.spec == P(MODEL_AXIS, None)
        assert mask is not None and mask.shape == (10,)
        assert np.asarray(mask).sum() == 1
        np.testing.assert_allclose(np.asarray(staged)[:9], arr)
        np.testing.assert_allclose(np.asarray(staged)[9:], 0.0)

    def test_unpadded_has_no_mask(self, ctx42):
        staged, mask = partition.stage_factor_matrix(
            ctx42, np.zeros((8, 4), np.float32)
        )
        assert staged.shape == (8, 4)
        assert mask is None

    def test_resident_sharded_array_passes_through(self, ctx42):
        arr = jax.device_put(
            np.zeros((8, 4), np.float32),
            NamedSharding(ctx42.mesh, P(MODEL_AXIS, None)),
        )
        staged, mask = partition.stage_factor_matrix(ctx42, arr, n_real=6)
        assert staged is arr  # no host round-trip, no copy
        assert mask is not None and np.asarray(mask).sum() == 2

    def test_resident_non_multiple_rejected(self, ctx42):
        arr = jax.device_put(np.zeros((9, 4), np.float32))
        with pytest.raises(ValueError, match="not a multiple"):
            partition.stage_factor_matrix(ctx42, arr)


class TestShardRowsPadding:
    def test_smaller_than_device_count_pads_and_shards(self, ctx8):
        """3 rows over 8 devices: pad-and-shard (one row per device),
        never a silent replicated fallback — with the padding counted
        in pio_mesh_pad_rows_total."""
        counter = get_registry().counter(
            "pio_mesh_pad_rows_total",
            "Phantom rows added when padding arrays to a mesh-axis "
            "multiple (shard_rows / sharded factor staging)",
        )
        before = counter.value
        arr = np.arange(6, dtype=np.float32).reshape(3, 2)
        out = ctx8.shard_rows(arr)
        assert out.shape == (8, 2)
        shard_rows = {s.data.shape[0] for s in out.addressable_shards}
        assert shard_rows == {1}  # genuinely sharded, one row each
        np.testing.assert_allclose(np.asarray(out)[:3], arr)
        np.testing.assert_allclose(np.asarray(out)[3:], 0.0)
        assert counter.value == before + 5

    def test_multiple_rows_unpadded_uncounted(self, ctx8):
        counter = get_registry().counter(
            "pio_mesh_pad_rows_total",
            "Phantom rows added when padding arrays to a mesh-axis "
            "multiple (shard_rows / sharded factor staging)",
        )
        before = counter.value
        out = ctx8.shard_rows(np.zeros((16, 2), np.float32))
        assert out.shape == (16, 2)
        assert counter.value == before


class TestPhantomInvariant:
    def test_zero_tail_passes(self):
        arr = np.zeros((6, 3), np.float32)
        arr[:4] = 1.0
        assert_phantom_rows_zero(arr, 4)

    def test_nonzero_phantom_raises(self):
        arr = np.zeros((6, 3), np.float32)
        arr[5, 1] = 1e-8  # any nonzero, however small
        with pytest.raises(AssertionError, match="phantom-row"):
            assert_phantom_rows_zero(arr, 4, "item factors")


class TestForceHostDevices:
    """utils/hostdevices.py — the one shared pre-jax-import pinning
    contract (conftest, dryrun, multichip workers, child processes)."""

    def test_sets_when_absent(self, monkeypatch):
        from predictionio_tpu.utils.hostdevices import (
            force_host_platform_device_count,
        )

        monkeypatch.setenv("XLA_FLAGS", "--xla_foo=1")
        force_host_platform_device_count(4)
        assert (
            "--xla_force_host_platform_device_count=4"
            in __import__("os").environ["XLA_FLAGS"]
        )
        assert "--xla_foo=1" in __import__("os").environ["XLA_FLAGS"]

    def test_minimum_mode_never_shrinks(self, monkeypatch):
        import os

        from predictionio_tpu.utils.hostdevices import (
            force_host_platform_device_count,
        )

        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        force_host_platform_device_count(2)
        assert "count=8" in os.environ["XLA_FLAGS"]
        force_host_platform_device_count(16)
        assert "count=16" in os.environ["XLA_FLAGS"]

    def test_exact_mode_rewrites(self, monkeypatch):
        import os

        from predictionio_tpu.utils.hostdevices import (
            force_host_platform_device_count,
        )

        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        force_host_platform_device_count(2, exact=True)
        assert "count=2" in os.environ["XLA_FLAGS"]
        with pytest.raises(ValueError):
            force_host_platform_device_count(0)
