"""SQLite storage backend — the durable zero-dependency default.

Plays the role of the reference's JDBC backend
(``data/.../storage/jdbc/*.scala``, 1,332 LoC: scalikejdbc against
PostgreSQL/MySQL) using Python's stdlib ``sqlite3``. Like the reference's
``JDBCLEvents`` it keeps one event table per (app, channel)
(``JDBCLEvents.scala`` table name ``<namespace>_<appId>[_<channelId>]``),
indexed by event time for time-range scans, and stores all seven metadata
DAO tables plus the model blob store in the same file.

Thread-safety: one connection per thread via ``threading.local`` (sqlite
connections are not shareable across threads); WAL mode so the event
server's concurrent reader/writer threads do not serialize on the file.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import sqlite3
import threading
import uuid
from typing import Iterator, Sequence

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeysBackend,
    App,
    AppsBackend,
    Channel,
    ChannelsBackend,
    EngineInstance,
    EngineInstancesBackend,
    EngineManifest,
    EngineManifestsBackend,
    EvaluationInstance,
    EvaluationInstancesBackend,
    EventsBackend,
    Model,
    ModelsBackend,
)


def _iso(t: _dt.datetime) -> str:
    # Naive datetimes are UTC by convention (same rule as Event.__post_init__)
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return t.astimezone(_dt.timezone.utc).isoformat()


def _from_iso(s: str) -> _dt.datetime:
    return _dt.datetime.fromisoformat(s)


class SQLiteClient:
    """Shared connection manager for all DAOs of one storage source."""

    def __init__(self, config: dict | None = None):
        config = config or {}
        path = config.get("PATH") or config.get(
            "URL", os.path.join(os.getcwd(), "pio.sqlite")
        )
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self._local = threading.local()
        self._init_lock = threading.Lock()
        self._ensure_schema()

    @property
    def conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def _ensure_schema(self) -> None:
        with self._init_lock, self.conn as c:
            c.executescript(
                """
                CREATE TABLE IF NOT EXISTS apps (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  name TEXT UNIQUE NOT NULL,
                  description TEXT);
                CREATE TABLE IF NOT EXISTS access_keys (
                  key TEXT PRIMARY KEY,
                  appid INTEGER NOT NULL,
                  events TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS channels (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  name TEXT NOT NULL,
                  appid INTEGER NOT NULL,
                  UNIQUE(name, appid));
                CREATE TABLE IF NOT EXISTS engine_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT, start_time TEXT, end_time TEXT,
                  engine_id TEXT, engine_version TEXT, engine_variant TEXT,
                  engine_factory TEXT, batch TEXT, env TEXT, mesh_conf TEXT,
                  data_source_params TEXT, preparator_params TEXT,
                  algorithms_params TEXT, serving_params TEXT);
                CREATE TABLE IF NOT EXISTS evaluation_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT, start_time TEXT, end_time TEXT,
                  evaluation_class TEXT, engine_params_generator_class TEXT,
                  batch TEXT, env TEXT, evaluator_results TEXT,
                  evaluator_results_html TEXT, evaluator_results_json TEXT);
                CREATE TABLE IF NOT EXISTS engine_manifests (
                  id TEXT NOT NULL,
                  version TEXT NOT NULL,
                  name TEXT NOT NULL,
                  description TEXT,
                  files TEXT NOT NULL,
                  engine_factory TEXT NOT NULL,
                  PRIMARY KEY (id, version));
                CREATE TABLE IF NOT EXISTS models (
                  id TEXT PRIMARY KEY,
                  models BLOB NOT NULL);
                """
            )

    def event_table(self, app_id: int, channel_id: int | None) -> str:
        # Reference JDBC table naming: <namespace>_<appId>[_<channelId>]
        return f"events_{app_id}" + (
            f"_{channel_id}" if channel_id is not None else ""
        )


class SQLiteApps(AppsBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, app: App) -> int | None:
        try:
            with self._c.conn as c:
                if app.id > 0:
                    c.execute(
                        "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                    return app.id
                cur = c.execute(
                    "INSERT INTO apps (name, description) VALUES (?,?)",
                    (app.name, app.description),
                )
                return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def _row(self, r) -> App:
        return App(id=r[0], name=r[1], description=r[2])

    def get(self, app_id: int) -> App | None:
        r = self._c.conn.execute(
            "SELECT id, name, description FROM apps WHERE id=?", (app_id,)
        ).fetchone()
        return self._row(r) if r else None

    def get_by_name(self, name: str) -> App | None:
        r = self._c.conn.execute(
            "SELECT id, name, description FROM apps WHERE name=?", (name,)
        ).fetchone()
        return self._row(r) if r else None

    def get_all(self) -> list[App]:
        rows = self._c.conn.execute(
            "SELECT id, name, description FROM apps ORDER BY id"
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, app: App) -> bool:
        with self._c.conn as c:
            cur = c.execute(
                "UPDATE apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM apps WHERE id=?", (app_id,)
            ).rowcount > 0


class SQLiteAccessKeys(AccessKeysBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, access_key: AccessKey) -> str | None:
        key = access_key.key or self.generate_key()
        try:
            with self._c.conn as c:
                c.execute(
                    "INSERT INTO access_keys (key, appid, events) VALUES (?,?,?)",
                    (key, access_key.appid, json.dumps(list(access_key.events))),
                )
            return key
        except sqlite3.IntegrityError:
            return None

    def _row(self, r) -> AccessKey:
        return AccessKey(key=r[0], appid=r[1], events=tuple(json.loads(r[2])))

    def get(self, key: str) -> AccessKey | None:
        r = self._c.conn.execute(
            "SELECT key, appid, events FROM access_keys WHERE key=?", (key,)
        ).fetchone()
        return self._row(r) if r else None

    def get_all(self) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._c.conn.execute(
                "SELECT key, appid, events FROM access_keys"
            ).fetchall()
        ]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._c.conn.execute(
                "SELECT key, appid, events FROM access_keys WHERE appid=?",
                (app_id,),
            ).fetchall()
        ]

    def update(self, access_key: AccessKey) -> bool:
        with self._c.conn as c:
            cur = c.execute(
                "UPDATE access_keys SET appid=?, events=? WHERE key=?",
                (
                    access_key.appid,
                    json.dumps(list(access_key.events)),
                    access_key.key,
                ),
            )
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM access_keys WHERE key=?", (key,)
            ).rowcount > 0


class SQLiteChannels(ChannelsBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        try:
            with self._c.conn as c:
                if channel.id > 0:
                    c.execute(
                        "INSERT INTO channels (id, name, appid) VALUES (?,?,?)",
                        (channel.id, channel.name, channel.appid),
                    )
                    return channel.id
                cur = c.execute(
                    "INSERT INTO channels (name, appid) VALUES (?,?)",
                    (channel.name, channel.appid),
                )
                return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, channel_id: int) -> Channel | None:
        r = self._c.conn.execute(
            "SELECT id, name, appid FROM channels WHERE id=?", (channel_id,)
        ).fetchone()
        return Channel(id=r[0], name=r[1], appid=r[2]) if r else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            Channel(id=r[0], name=r[1], appid=r[2])
            for r in self._c.conn.execute(
                "SELECT id, name, appid FROM channels WHERE appid=?",
                (app_id,),
            ).fetchall()
        ]

    def delete(self, channel_id: int) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM channels WHERE id=?", (channel_id,)
            ).rowcount > 0


_EI_COLS = (
    "id status start_time end_time engine_id engine_version engine_variant "
    "engine_factory batch env mesh_conf data_source_params preparator_params "
    "algorithms_params serving_params"
).split()


class SQLiteEngineInstances(EngineInstancesBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def _to_row(self, i: EngineInstance):
        return (
            i.id, i.status, _iso(i.start_time), _iso(i.end_time),
            i.engine_id, i.engine_version, i.engine_variant,
            i.engine_factory, i.batch, json.dumps(i.env),
            json.dumps(i.mesh_conf), i.data_source_params,
            i.preparator_params, i.algorithms_params, i.serving_params,
        )

    def _from_row(self, r) -> EngineInstance:
        return EngineInstance(
            id=r[0], status=r[1],
            start_time=_from_iso(r[2]), end_time=_from_iso(r[3]),
            engine_id=r[4], engine_version=r[5], engine_variant=r[6],
            engine_factory=r[7], batch=r[8], env=json.loads(r[9]),
            mesh_conf=json.loads(r[10]), data_source_params=r[11],
            preparator_params=r[12], algorithms_params=r[13],
            serving_params=r[14],
        )

    def insert(self, instance: EngineInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        row = (iid,) + self._to_row(instance)[1:]
        with self._c.conn as c:
            c.execute(
                f"INSERT OR REPLACE INTO engine_instances "
                f"({','.join(_EI_COLS)}) VALUES ({','.join('?' * len(_EI_COLS))})",
                row,
            )
        return iid

    def get(self, instance_id: str) -> EngineInstance | None:
        r = self._c.conn.execute(
            f"SELECT {','.join(_EI_COLS)} FROM engine_instances WHERE id=?",
            (instance_id,),
        ).fetchone()
        return self._from_row(r) if r else None

    def get_all(self) -> list[EngineInstance]:
        return [
            self._from_row(r)
            for r in self._c.conn.execute(
                f"SELECT {','.join(_EI_COLS)} FROM engine_instances"
            ).fetchall()
        ]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        rows = self._c.conn.execute(
            f"SELECT {','.join(_EI_COLS)} FROM engine_instances "
            "WHERE status='COMPLETED' AND engine_id=? AND engine_version=? "
            "AND engine_variant=? ORDER BY start_time DESC",
            (engine_id, engine_version, engine_variant),
        ).fetchall()
        return [self._from_row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(
            engine_id, engine_version, engine_variant
        )
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> bool:
        sets = ",".join(f"{c}=?" for c in _EI_COLS[1:])
        with self._c.conn as c:
            cur = c.execute(
                f"UPDATE engine_instances SET {sets} WHERE id=?",
                self._to_row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM engine_instances WHERE id=?", (instance_id,)
            ).rowcount > 0


_EM_COLS = "id version name description files engine_factory".split()


class SQLiteEngineManifests(EngineManifestsBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def _from_row(self, r) -> EngineManifest:
        return EngineManifest(
            id=r[0], version=r[1], name=r[2], description=r[3],
            files=tuple(json.loads(r[4])), engine_factory=r[5],
        )

    def insert(self, manifest: EngineManifest) -> None:
        with self._c.conn as c:
            c.execute(
                f"INSERT OR REPLACE INTO engine_manifests "
                f"({','.join(_EM_COLS)}) VALUES (?,?,?,?,?,?)",
                (
                    manifest.id, manifest.version, manifest.name,
                    manifest.description, json.dumps(list(manifest.files)),
                    manifest.engine_factory,
                ),
            )

    def get(self, manifest_id: str, version: str) -> EngineManifest | None:
        row = self._c.conn.execute(
            f"SELECT {','.join(_EM_COLS)} FROM engine_manifests "
            "WHERE id=? AND version=?",
            (manifest_id, version),
        ).fetchone()
        return self._from_row(row) if row else None

    def get_all(self) -> list[EngineManifest]:
        rows = self._c.conn.execute(
            f"SELECT {','.join(_EM_COLS)} FROM engine_manifests"
        ).fetchall()
        return [self._from_row(r) for r in rows]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        if not upsert and self.get(manifest.id, manifest.version) is None:
            raise KeyError(
                f"engine manifest ({manifest.id}, {manifest.version}) "
                "not found"
            )
        self.insert(manifest)

    def delete(self, manifest_id: str, version: str) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM engine_manifests WHERE id=? AND version=?",
                (manifest_id, version),
            ).rowcount > 0


_EVI_COLS = (
    "id status start_time end_time evaluation_class "
    "engine_params_generator_class batch env evaluator_results "
    "evaluator_results_html evaluator_results_json"
).split()


class SQLiteEvaluationInstances(EvaluationInstancesBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def _to_row(self, i: EvaluationInstance):
        return (
            i.id, i.status, _iso(i.start_time), _iso(i.end_time),
            i.evaluation_class, i.engine_params_generator_class, i.batch,
            json.dumps(i.env), i.evaluator_results,
            i.evaluator_results_html, i.evaluator_results_json,
        )

    def _from_row(self, r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0], status=r[1],
            start_time=_from_iso(r[2]), end_time=_from_iso(r[3]),
            evaluation_class=r[4], engine_params_generator_class=r[5],
            batch=r[6], env=json.loads(r[7]), evaluator_results=r[8],
            evaluator_results_html=r[9], evaluator_results_json=r[10],
        )

    def insert(self, instance: EvaluationInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        row = (iid,) + self._to_row(instance)[1:]
        with self._c.conn as c:
            c.execute(
                f"INSERT OR REPLACE INTO evaluation_instances "
                f"({','.join(_EVI_COLS)}) VALUES ({','.join('?' * len(_EVI_COLS))})",
                row,
            )
        return iid

    def get(self, instance_id: str) -> EvaluationInstance | None:
        r = self._c.conn.execute(
            f"SELECT {','.join(_EVI_COLS)} FROM evaluation_instances WHERE id=?",
            (instance_id,),
        ).fetchone()
        return self._from_row(r) if r else None

    def get_all(self) -> list[EvaluationInstance]:
        return [
            self._from_row(r)
            for r in self._c.conn.execute(
                f"SELECT {','.join(_EVI_COLS)} FROM evaluation_instances"
            ).fetchall()
        ]

    def get_completed(self) -> list[EvaluationInstance]:
        rows = self._c.conn.execute(
            f"SELECT {','.join(_EVI_COLS)} FROM evaluation_instances "
            "WHERE status='EVALCOMPLETED' ORDER BY start_time DESC"
        ).fetchall()
        return [self._from_row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> bool:
        sets = ",".join(f"{c}=?" for c in _EVI_COLS[1:])
        with self._c.conn as c:
            cur = c.execute(
                f"UPDATE evaluation_instances SET {sets} WHERE id=?",
                self._to_row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM evaluation_instances WHERE id=?", (instance_id,)
            ).rowcount > 0


class SQLiteModels(ModelsBackend):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, model: Model) -> None:
        with self._c.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO models (id, models) VALUES (?,?)",
                (model.id, model.models),
            )

    def get(self, model_id: str) -> Model | None:
        r = self._c.conn.execute(
            "SELECT id, models FROM models WHERE id=?", (model_id,)
        ).fetchone()
        return Model(id=r[0], models=r[1]) if r else None

    def delete(self, model_id: str) -> bool:
        with self._c.conn as c:
            return c.execute(
                "DELETE FROM models WHERE id=?", (model_id,)
            ).rowcount > 0


class SQLiteEvents(EventsBackend):
    """Event DAO over per-(app, channel) tables indexed by event time
    (reference JDBCLEvents.scala init/insert/find)."""

    def __init__(self, client: SQLiteClient):
        self._c = client

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        t = self._c.event_table(app_id, channel_id)
        with self._c.conn as c:
            c.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS {t} (
                  id TEXT PRIMARY KEY,
                  event TEXT NOT NULL,
                  entity_type TEXT NOT NULL,
                  entity_id TEXT NOT NULL,
                  target_entity_type TEXT,
                  target_entity_id TEXT,
                  properties TEXT NOT NULL,
                  event_time TEXT NOT NULL,
                  tags TEXT NOT NULL,
                  pr_id TEXT,
                  creation_time TEXT NOT NULL);
                CREATE INDEX IF NOT EXISTS {t}_time ON {t} (event_time);
                CREATE INDEX IF NOT EXISTS {t}_entity
                  ON {t} (entity_type, entity_id);
                """
            )
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        t = self._c.event_table(app_id, channel_id)
        with self._c.conn as c:
            c.execute(f"DROP TABLE IF EXISTS {t}")
        return True

    def close(self) -> None:
        pass

    def _to_row(self, e: Event):
        return (
            e.event_id, e.event, e.entity_type, e.entity_id,
            e.target_entity_type, e.target_entity_id,
            json.dumps(e.properties.to_dict()), _iso(e.event_time),
            json.dumps(list(e.tags)), e.pr_id, _iso(e.creation_time),
        )

    def _from_row(self, r) -> Event:
        return Event(
            event_id=r[0], event=r[1], entity_type=r[2], entity_id=r[3],
            target_entity_type=r[4], target_entity_id=r[5],
            properties=DataMap(json.loads(r[6])),
            event_time=_from_iso(r[7]), tags=tuple(json.loads(r[8])),
            pr_id=r[9], creation_time=_from_iso(r[10]),
        )

    def insert(
        self, event: Event, app_id: int, channel_id: int | None = None
    ) -> str:
        stamped = event.with_id(event.event_id)
        t = self._c.event_table(app_id, channel_id)
        sql = f"INSERT OR REPLACE INTO {t} VALUES ({','.join('?' * 11)})"
        try:
            with self._c.conn as c:
                c.execute(sql, self._to_row(stamped))
        except sqlite3.OperationalError:
            # table not yet init()-ed — auto-create, matching MemoryEvents
            self.init(app_id, channel_id)
            with self._c.conn as c:
                c.execute(sql, self._to_row(stamped))
        return stamped.event_id

    def insert_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: int | None = None,
    ) -> list[str]:
        stamped = [e.with_id(e.event_id) for e in events]
        t = self._c.event_table(app_id, channel_id)
        sql = f"INSERT OR REPLACE INTO {t} VALUES ({','.join('?' * 11)})"
        rows = [self._to_row(e) for e in stamped]
        try:
            with self._c.conn as c:
                c.executemany(sql, rows)
        except sqlite3.OperationalError:
            self.init(app_id, channel_id)
            with self._c.conn as c:
                c.executemany(sql, rows)
        return [e.event_id for e in stamped]

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        t = self._c.event_table(app_id, channel_id)
        try:
            r = self._c.conn.execute(
                f"SELECT * FROM {t} WHERE id=?", (event_id,)
            ).fetchone()
        except sqlite3.OperationalError:
            return None
        return self._from_row(r) if r else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        t = self._c.event_table(app_id, channel_id)
        with self._c.conn as c:
            try:
                return c.execute(
                    f"DELETE FROM {t} WHERE id=?", (event_id,)
                ).rowcount > 0
            except sqlite3.OperationalError:
                return False

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        t = self._c.event_table(app_id, channel_id)
        where, params = [], []
        if start_time is not None:
            where.append("event_time >= ?")
            params.append(_iso(start_time))
        if until_time is not None:
            where.append("event_time < ?")
            params.append(_iso(until_time))
        if entity_type is not None:
            where.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            where.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            where.append(
                f"event IN ({','.join('?' * len(event_names))})"
            )
            params.extend(event_names)
        if target_entity_type is not ...:
            if target_entity_type is None:
                where.append("target_entity_type IS NULL")
            else:
                where.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not ...:
            if target_entity_id is None:
                where.append("target_entity_id IS NULL")
            else:
                where.append("target_entity_id = ?")
                params.append(target_entity_id)
        sql = f"SELECT * FROM {t}"
        if where:
            sql += " WHERE " + " AND ".join(where)
        sql += f" ORDER BY event_time {'DESC' if reversed else 'ASC'}"
        if limit is not None and limit > 0:
            sql += f" LIMIT {int(limit)}"
        elif limit == 0:
            return
        try:
            cur = self._c.conn.execute(sql, params)
        except sqlite3.OperationalError:
            return  # table not initialized → no events
        for r in cur:
            yield self._from_row(r)
