"""Tests for the ``pio-tpu lint`` static analyzer
(predictionio_tpu/analysis/): per-rule positive + negative fixtures,
suppression syntax, baseline round-trip, the seeded two-lock deadlock
cycle, and meta-tests that the shipped baseline parses and the real
tree is clean.

Pure stdlib — no jax import anywhere on this path.
"""

from __future__ import annotations

import os
import textwrap

import pytest

from predictionio_tpu.analysis import (
    BaselineError,
    analyze_modules,
    load_baseline,
    render_baseline,
    run_lint,
)
from predictionio_tpu.analysis.baseline import split_by_baseline
from predictionio_tpu.analysis.source import SourceModule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_source(src: str, path: str = "mod.py", extra: dict | None = None):
    """Findings for one (or more) in-memory fixture modules."""
    sources = {path: src, **(extra or {})}
    modules = [
        SourceModule(f"/fixture/{p}", p, textwrap.dedent(text))
        for p, text in sources.items()
    ]
    return analyze_modules(modules)


def rules_of(findings):
    return [f.rule for f in findings]


# -- lock-order ------------------------------------------------------------


class TestLockOrder:
    def test_seeded_two_lock_cycle_detected(self):
        """The acceptance-criteria fixture: A->B in one method, B->A in
        another, must report a potential deadlock."""
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            return 1

                def two(self):
                    with self._b:
                        with self._a:
                            return 2
            """
        )
        cycles = [f for f in findings if f.rule == "lock-order"]
        assert len(cycles) == 1
        assert "W._a" in cycles[0].message
        assert "W._b" in cycles[0].message

    def test_cycle_via_same_module_call(self):
        """Interprocedural: two() holds _b and calls helper(), which
        acquires _a — closes the cycle against one()."""
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            return 1

                def two(self):
                    with self._b:
                        self.helper()

                def helper(self):
                    with self._a:
                        return 2
            """
        )
        assert "lock-order" in rules_of(findings)

    def test_consistent_order_is_clean(self):
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            return 1

                def two(self):
                    with self._a:
                        with self._b:
                            return 2
            """
        )
        assert "lock-order" not in rules_of(findings)

    def test_nonreentrant_self_cycle(self):
        """with self._lock: self.locked_helper() where the helper
        re-acquires the same plain Lock = guaranteed deadlock."""
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        return 1
            """
        )
        assert "lock-order" in rules_of(findings)

    def test_rlock_reentry_is_clean(self):
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        return 1
            """
        )
        assert "lock-order" not in rules_of(findings)

    def test_multi_item_with_orders_left_to_right(self):
        """`with a, b:` + `with b, a:` elsewhere is still a cycle."""
        findings = lint_source(
            """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A, B:
                    return 1

            def two():
                with B, A:
                    return 2
            """
        )
        assert "lock-order" in rules_of(findings)


# -- lock-blocking ---------------------------------------------------------


class TestLockBlocking:
    def test_sleep_under_lock(self):
        findings = lint_source(
            """
            import threading
            import time

            _lock = threading.Lock()

            def f():
                with _lock:
                    time.sleep(1)
            """
        )
        assert "lock-blocking" in rules_of(findings)

    def test_future_result_under_lock(self):
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def f(self, future):
                    with self._lock:
                        return future.result(timeout=5)
            """
        )
        assert "lock-blocking" in rules_of(findings)

    def test_device_barrier_under_lock(self):
        findings = lint_source(
            """
            import threading
            import jax

            _lock = threading.Lock()

            def f(x):
                with _lock:
                    return jax.device_get(x)
            """
        )
        assert "lock-blocking" in rules_of(findings)

    def test_interprocedural_blocking_callee(self):
        findings = lint_source(
            """
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def f(self):
                    with self._lock:
                        self.slow()

                def slow(self):
                    time.sleep(2)
            """
        )
        blocked = [f for f in findings if f.rule == "lock-blocking"]
        assert any("slow" in f.message for f in blocked)

    def test_sleep_outside_lock_is_clean(self):
        findings = lint_source(
            """
            import threading
            import time

            _lock = threading.Lock()

            def f():
                with _lock:
                    snapshot = 1
                time.sleep(snapshot)
            """
        )
        assert "lock-blocking" not in rules_of(findings)

    def test_unbounded_queue_put_is_clean_bounded_get_flags(self):
        findings = lint_source(
            """
            import queue
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()
                    self._bq = queue.Queue(maxsize=8)

                def ok(self, item):
                    with self._lock:
                        self._q.put(item)

                def bad(self):
                    with self._lock:
                        return self._bq.get()
            """
        )
        blocked = [f for f in findings if f.rule == "lock-blocking"]
        assert len(blocked) == 1
        assert ".get()" in blocked[0].message

    def test_str_join_and_dict_get_are_clean(self):
        findings = lint_source(
            """
            import threading

            _lock = threading.Lock()

            def f(d):
                with _lock:
                    return ", ".join(d) + str(d.get("k"))
            """
        )
        assert "lock-blocking" not in rules_of(findings)

    def test_blocking_in_except_handler_reported_once(self):
        """Handler bodies are reachable two ways in the walker — the
        finding must still be reported exactly once (duplicates would
        double-count in the baseline and CI summary)."""
        findings = lint_source(
            """
            import threading
            import time

            _lock = threading.Lock()

            def f():
                with _lock:
                    try:
                        work()
                    except ValueError:
                        time.sleep(1)
            """
        )
        blocked = [f for f in findings if f.rule == "lock-blocking"]
        assert len(blocked) == 1

    def test_condition_wait_releases_its_own_lock(self):
        findings = lint_source(
            """
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def f(self):
                    with self._cond:
                        self._cond.wait(timeout=1)
            """
        )
        assert "lock-blocking" not in rules_of(findings)


# -- wall-clock ------------------------------------------------------------


class TestWallClock:
    def test_elapsed_arithmetic_flagged(self):
        findings = lint_source(
            """
            import time

            def f(t0):
                return time.time() - t0
            """
        )
        assert "wall-clock" in rules_of(findings)

    def test_deadline_comparison_flagged(self):
        findings = lint_source(
            """
            import time

            def f(deadline):
                while time.time() < deadline:
                    pass
            """
        )
        assert "wall-clock" in rules_of(findings)

    def test_anchor_assignment_flagged(self):
        findings = lint_source(
            """
            import time

            class S:
                def __init__(self):
                    self._start_time = time.time()
            """
        )
        assert "wall-clock" in rules_of(findings)

    def test_backoff_function_flagged(self):
        findings = lint_source(
            """
            import time

            def next_backoff():
                return time.time()
            """
        )
        assert "wall-clock" in rules_of(findings)

    def test_display_timestamp_is_clean(self):
        """A log-record ts field is display-only wall clock — fine."""
        findings = lint_source(
            """
            import time

            def log_record(event):
                return {"event": event, "ts": round(time.time(), 3)}
            """
        )
        assert "wall-clock" not in rules_of(findings)

    def test_monotonic_is_clean(self):
        findings = lint_source(
            """
            import time

            def f(t0):
                return time.monotonic() - t0
            """
        )
        assert "wall-clock" not in rules_of(findings)


# -- device-sync -----------------------------------------------------------


class TestDeviceSync:
    def test_item_inside_jit(self):
        findings = lint_source(
            """
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
            """
        )
        assert "device-sync-jit" in rules_of(findings)

    def test_float_of_traced_value_inside_jit(self):
        findings = lint_source(
            """
            import jax

            @jax.jit
            def f(x):
                y = x * 2
                return float(y)
            """
        )
        assert "device-sync-jit" in rules_of(findings)

    def test_float_of_host_closure_is_clean(self):
        """float(max(n, 1)) on a host closure value inside jit is fine
        (the complementarypurchase lift scaling pattern)."""
        findings = lint_source(
            """
            import jax

            n_baskets = 10

            @jax.jit
            def f(x):
                return x * float(max(n_baskets, 1))
            """
        )
        assert "device-sync-jit" not in rules_of(findings)

    def test_partial_jit_decorator_np_asarray(self):
        findings = lint_source(
            """
            import functools
            import jax
            import numpy as np

            @functools.partial(jax.jit, static_argnums=(1,))
            def f(x, k):
                return np.asarray(x)
            """
        )
        assert "device-sync-jit" in rules_of(findings)

    def test_call_form_jit_detected(self):
        """ops/als.py style: ``return jax.jit(body)`` — the wrapped
        function is jit scope even without a decorator."""
        findings = lint_source(
            """
            import jax

            def make_step():
                def body(x):
                    return x.sum().item()
                return jax.jit(body)
            """
        )
        assert "device-sync-jit" in rules_of(findings)

    def test_launch_hook_device_get_flagged(self):
        findings = lint_source(
            """
            import jax

            class Algo:
                def batch_predict_launch(self, queries):
                    out = self._jitted(queries)
                    return jax.device_get(out)
            """
        )
        assert "device-sync-hot" in rules_of(findings)

    def test_two_phase_dispatch_blocking_flagged(self):
        findings = lint_source(
            """
            class TwoPhase:
                def dispatch(self, items):
                    handle = self._enqueue(items)
                    handle.block_until_ready()
                    return handle

                def collect(self, handle):
                    return handle
            """
        )
        assert "device-sync-hot" in rules_of(findings)

    def test_launch_host_prep_is_clean(self):
        """np.asarray on host inputs is legitimate prep in launch —
        only explicit syncs violate the enqueue-only contract."""
        findings = lint_source(
            """
            import numpy as np

            class Algo:
                def batch_predict_launch(self, queries):
                    ids = np.asarray([q["id"] for q in queries])
                    return self._jitted(ids)
            """
        )
        assert "device-sync-hot" not in rules_of(findings)

    def test_plain_dispatch_without_collect_is_clean(self):
        findings = lint_source(
            """
            class NotTwoPhase:
                def dispatch(self, handler):
                    return handler.result()
            """
        )
        assert "device-sync-hot" not in rules_of(findings)


# -- thread-lifecycle ------------------------------------------------------


class TestThreadLifecycle:
    def test_undaemonized_unjoined_flagged(self):
        findings = lint_source(
            """
            import threading

            class S:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()
            """
        )
        assert "thread-lifecycle" in rules_of(findings)

    def test_daemon_true_is_clean(self):
        findings = lint_source(
            """
            import threading

            def go(fn):
                threading.Thread(target=fn, daemon=True).start()
            """
        )
        assert "thread-lifecycle" not in rules_of(findings)

    def test_joined_in_close_is_clean(self):
        findings = lint_source(
            """
            import threading

            class S:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

                def close(self):
                    self._thread.join(timeout=5)
            """
        )
        assert "thread-lifecycle" not in rules_of(findings)

    def test_local_thread_joined_same_function_is_clean(self):
        findings = lint_source(
            """
            import threading

            def run(fn):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
            """
        )
        assert "thread-lifecycle" not in rules_of(findings)

    def test_unbound_undaemonized_flagged(self):
        findings = lint_source(
            """
            import threading

            def fire(fn):
                threading.Thread(target=fn).start()
            """
        )
        assert "thread-lifecycle" in rules_of(findings)


# -- telemetry hygiene -----------------------------------------------------


class TestTelemetry:
    def test_span_without_with_flagged(self):
        findings = lint_source(
            """
            from predictionio_tpu.obs import tracing

            def f():
                sp = tracing.span("work")
                do_work()
            """
        )
        assert "span-leak" in rules_of(findings)

    def test_span_in_with_is_clean(self):
        findings = lint_source(
            """
            from predictionio_tpu.obs import tracing

            def f():
                with tracing.span("work"):
                    do_work()
            """
        )
        assert "span-leak" not in rules_of(findings)

    def test_span_cm_variable_pattern_is_clean(self):
        """The http.py/router.py pattern: bind the cm (possibly via a
        conditional expression), enter it later."""
        findings = lint_source(
            """
            from predictionio_tpu.obs import tracing

            def f(tracer, parent, enabled):
                span_cm = (
                    tracer.child(parent, "hop")
                    if enabled
                    else tracing.NOOP
                )
                with span_cm as sp:
                    do_work(sp)
            """
        )
        assert "span-leak" not in rules_of(findings)

    def test_span_factory_return_is_clean(self):
        findings = lint_source(
            """
            from predictionio_tpu.obs import tracing

            def make(tracer, parent):
                return tracer.child(parent, "hop")
            """
        )
        assert "span-leak" not in rules_of(findings)

    def test_metric_label_conflict_flagged(self):
        extra = {
            "b.py": """
            from predictionio_tpu.obs.registry import default_registry

            registry = default_registry()
            c = registry.counter("pio_things_total", "things", ("kind",))
            """
        }
        findings = lint_source(
            """
            from predictionio_tpu.obs.registry import default_registry

            registry = default_registry()
            c = registry.counter("pio_things_total", "things")
            """,
            path="a.py",
            extra=extra,
        )
        conflicts = [f for f in findings if f.rule == "metric-labels"]
        assert len(conflicts) == 2  # one per conflicting site
        assert {f.path for f in conflicts} == {"a.py", "b.py"}

    def test_metric_kind_conflict_flagged(self):
        extra = {
            "b.py": """
            registry = get_registry()
            g = registry.gauge("pio_depth", "depth")
            """
        }
        findings = lint_source(
            """
            registry = get_registry()
            c = registry.counter("pio_depth", "depth")
            """,
            path="a.py",
            extra=extra,
        )
        assert "metric-labels" in rules_of(findings)

    def test_consistent_metric_is_clean(self):
        extra = {
            "b.py": """
            registry = get_registry()
            c = registry.counter("pio_x_total", "x", ("a", "b"))
            """
        }
        findings = lint_source(
            """
            registry = get_registry()
            c = registry.counter("pio_x_total", "x", ("a", "b"))
            """,
            path="a.py",
            extra=extra,
        )
        assert "metric-labels" not in rules_of(findings)


# -- suppressions ----------------------------------------------------------


class TestSuppressions:
    SRC = """
    import time

    def f(t0):
        return time.time() - t0{suffix}
    """

    def test_same_line_suppression(self):
        findings = lint_source(
            self.SRC.format(
                suffix="  # pio-lint: disable=wall-clock -- test reason"
            )
        )
        assert findings == []

    def test_disable_next_line(self):
        findings = lint_source(
            """
            import time

            def f(t0):
                # pio-lint: disable-next=wall-clock -- reason
                return time.time() - t0
            """
        )
        assert findings == []

    def test_disable_file(self):
        findings = lint_source(
            """
            # pio-lint: disable-file=wall-clock
            import time

            def f(t0):
                return time.time() - t0
            """
        )
        assert findings == []

    def test_wrong_rule_does_not_suppress(self):
        findings = lint_source(
            self.SRC.format(suffix="  # pio-lint: disable=span-leak")
        )
        assert rules_of(findings) == ["wall-clock"]

    def test_all_wildcard(self):
        findings = lint_source(
            self.SRC.format(suffix="  # pio-lint: disable=all")
        )
        assert findings == []

    def test_marker_in_string_literal_is_not_a_suppression(self):
        findings = lint_source(
            """
            import time

            MSG = "# pio-lint: disable-file=wall-clock"

            def f(t0):
                return time.time() - t0
            """
        )
        assert rules_of(findings) == ["wall-clock"]


# -- baseline --------------------------------------------------------------


class TestBaseline:
    def _findings(self):
        return lint_source(
            """
            import time

            def f(t0):
                return time.time() - t0

            def g(t0):
                return time.time() - t0
            """
        )

    def test_round_trip(self, tmp_path):
        findings = self._findings()
        assert len(findings) == 2
        path = tmp_path / "baseline.txt"
        path.write_text(render_baseline(findings))
        entries = load_baseline(str(path))
        new, baselined, stale = split_by_baseline(findings, entries)
        assert new == []
        assert len(baselined) == 2
        assert stale == []

    def test_line_drift_still_matches(self, tmp_path):
        """Baseline matching ignores line numbers: adding code above a
        baselined site must not resurrect it."""
        findings = self._findings()
        path = tmp_path / "baseline.txt"
        path.write_text(render_baseline(findings))
        drifted = lint_source(
            """
            import time

            x = 1
            y = 2

            def f(t0):
                return time.time() - t0

            def g(t0):
                return time.time() - t0
            """
        )
        new, baselined, _stale = split_by_baseline(
            drifted, load_baseline(str(path))
        )
        assert new == []
        assert len(baselined) == 2

    def test_fixed_finding_goes_stale(self, tmp_path):
        findings = self._findings()
        path = tmp_path / "baseline.txt"
        path.write_text(render_baseline(findings))
        one_fixed = lint_source(
            """
            import time

            def f(t0):
                return time.monotonic() - t0

            def g(t0):
                return time.time() - t0
            """
        )
        new, baselined, stale = split_by_baseline(
            one_fixed, load_baseline(str(path))
        )
        assert new == []
        assert len(baselined) == 1
        assert len(stale) == 1

    def test_multiset_matching(self, tmp_path):
        """Two identical violations need two baseline entries — one
        entry must not absorb both."""
        findings = self._findings()
        path = tmp_path / "baseline.txt"
        # keep only ONE of the two entries
        lines = [
            ln
            for ln in render_baseline(findings).splitlines()
            if not ln.startswith("#")
        ]
        assert len(lines) == 2
        path.write_text(lines[0] + "\n")
        new, baselined, stale = split_by_baseline(
            findings, load_baseline(str(path))
        )
        assert len(new) == 1
        assert len(baselined) == 1
        assert stale == []

    def test_malformed_baseline_raises(self, tmp_path):
        path = tmp_path / "baseline.txt"
        path.write_text("not a baseline line\n")
        with pytest.raises(BaselineError):
            load_baseline(str(path))


# -- end-to-end + meta -----------------------------------------------------


class TestRunLintAndCli:
    def test_run_lint_over_fixture_dir(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\n\n\ndef f(t0):\n"
            "    return time.time() - t0\n"
        )
        result = run_lint([str(tmp_path)], root=str(tmp_path))
        assert result.files_checked == 1
        assert [f.rule for f in result.new] == ["wall-clock"]
        assert result.new[0].path == "bad.py"
        assert not result.ok

    def test_syntax_error_is_an_error_not_a_crash(self, tmp_path):
        (tmp_path / "broken.py").write_text("def f(:\n")
        result = run_lint([str(tmp_path)], root=str(tmp_path))
        assert result.errors
        assert not result.ok

    def test_cli_verb_json(self, tmp_path, capsys, monkeypatch):
        import json as _json

        from predictionio_tpu.cli.main import main

        (tmp_path / "bad.py").write_text(
            "import time\ndeadline = time.time() + 5\n"
        )
        monkeypatch.chdir(tmp_path)
        rc = main(["lint", "bad.py", "--no-baseline", "--json"])
        payload = _json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["ok"] is False
        assert payload["new"][0]["rule"] == "wall-clock"

    def test_cli_write_baseline_then_clean(self, tmp_path, capsys,
                                           monkeypatch):
        from predictionio_tpu.cli.main import main

        (tmp_path / "bad.py").write_text(
            "import time\ndeadline = time.time() + 5\n"
        )
        monkeypatch.chdir(tmp_path)
        baseline = str(tmp_path / "baseline.txt")
        assert main(["lint", "bad.py", "--baseline", baseline,
                     "--write-baseline"]) == 0
        assert main(["lint", "bad.py", "--baseline", baseline]) == 0
        capsys.readouterr()

    def test_cli_missing_path_is_usage_error(self, tmp_path, capsys,
                                             monkeypatch):
        from predictionio_tpu.cli.main import main

        monkeypatch.chdir(tmp_path)
        assert main(["lint", "nope_dir"]) == 2
        capsys.readouterr()


class TestRepoIsClean:
    """Meta-tests over the real tree — the same contract CI gates on."""

    def test_shipped_baseline_parses_and_is_live(self):
        path = os.path.join(REPO_ROOT, "scripts", "lint_baseline.txt")
        entries = load_baseline(path)  # must parse
        result = run_lint(
            [
                os.path.join(REPO_ROOT, "predictionio_tpu"),
                os.path.join(REPO_ROOT, "scripts"),
            ],
            root=REPO_ROOT,
            baseline_path=path,
        )
        # every baseline entry still matches a real location
        assert result.stale_baseline == [], [
            f"{e.rule}|{e.path}|{e.context}" for e in result.stale_baseline
        ]
        assert len(result.baselined) == len(entries)

    def test_tree_has_no_new_findings(self):
        result = run_lint(
            [
                os.path.join(REPO_ROOT, "predictionio_tpu"),
                os.path.join(REPO_ROOT, "scripts"),
            ],
            root=REPO_ROOT,
            baseline_path=os.path.join(
                REPO_ROOT, "scripts", "lint_baseline.txt"
            ),
        )
        assert result.errors == []
        assert result.new == [], "\n".join(
            f.render() for f in result.new
        )
