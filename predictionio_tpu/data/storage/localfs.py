"""Local-filesystem model blob store.

Counterpart of the reference's ``localfs`` backend
(``data/.../storage/localfs/LocalFSModels.scala``, model blobs as files
under ``PIO_FS_BASEDIR``). Model checkpoints written by orbax (sharded
array checkpoints) also live under this root — see
:mod:`predictionio_tpu.core.persistence`.
"""

from __future__ import annotations

import os

from predictionio_tpu.data.storage.base import Model, ModelsBackend


class LocalFSModels(ModelsBackend):
    def __init__(self, config: dict | None = None):
        config = config or {}
        base = config.get("PATH") or os.path.join(
            os.environ.get(
                "PIO_FS_BASEDIR",
                os.path.join(os.path.expanduser("~"), ".piotpu"),
            ),
            "models",
        )
        os.makedirs(base, exist_ok=True)
        self._base = base

    def _path(self, model_id: str) -> str:
        safe = model_id.replace("/", "_")
        return os.path.join(self._base, f"pio_model_{safe}.bin")

    def insert(self, model: Model) -> None:
        tmp = self._path(model.id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
        os.replace(tmp, self._path(model.id))

    def get(self, model_id: str) -> Model | None:
        try:
            with open(self._path(model_id), "rb") as f:
                return Model(id=model_id, models=f.read())
        except FileNotFoundError:
            return None

    def delete(self, model_id: str) -> bool:
        try:
            os.remove(self._path(model_id))
            return True
        except FileNotFoundError:
            return False
