// Native event log — append-only binary event store with a persistent
// string dictionary (interner) and columnar scans.
//
// Role in the framework: the high-write-throughput event store the
// reference delegates to HBase (data/.../storage/hbase, SURVEY.md §2.4)
// and the native data-loader path: scans return *columnar* arrays of
// interned ids — directly consumable as dense matrix indices — instead
// of per-event objects, solving the string-id→dense-index bottleneck at
// scale (SURVEY.md §7 hard-part (b): BiMap.collect "won't fly").
//
// Files per log directory:
//   dict.bin — length-prefixed strings; position = interned id
//   log.bin  — framed records (see layout below)
//
// Record layout (little-endian):
//   u32 total_len (bytes after this field)
//   u8  kind      (1 = put, 2 = delete-tombstone)
//   f64 event_time, f64 creation_time
//   u32 event, u32 entity_type, u32 entity_id          (dict ids)
//   i32 target_entity_type, i32 target_entity_id       (-1 = absent)
//   u32 id_len,   bytes event_id
//   u32 blob_len, bytes blob (JSON: properties/tags/prId)
//
// Thread-safety: callers serialize appends (the Python wrapper holds a
// lock); scans open their own read handle on the finished prefix.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#if defined(_WIN32)
#include <io.h>
#define PIO_FSYNC _commit
#define PIO_FILENO _fileno
#else
#include <unistd.h>
#define PIO_FSYNC fsync
#define PIO_FILENO fileno
#endif

namespace {

struct Log {
  std::string dir;
  FILE* log_file = nullptr;   // append handle
  FILE* dict_file = nullptr;  // append handle
  std::unordered_map<std::string, uint32_t> dict;
  std::vector<std::string> strings;
  long dict_offset = 0;  // how far into dict.bin we've read

  std::string log_path() const { return dir + "/log.bin"; }
  std::string dict_path() const { return dir + "/dict.bin"; }
};

// Incrementally read dict.bin from the last seen offset — ids are
// append-ordered, so entries written by other processes slot in at the
// positions they claimed (see the flock discipline in the wrapper).
bool load_dict(Log* log) {
  FILE* f = std::fopen(log->dict_path().c_str(), "rb");
  if (f == nullptr) return true;  // fresh log
  std::fseek(f, log->dict_offset, SEEK_SET);
  for (;;) {
    uint32_t len;
    if (std::fread(&len, 4, 1, f) != 1) break;
    std::string s(len, '\0');
    if (len > 0 && std::fread(&s[0], 1, len, f) != len) break;
    log->dict.emplace(s, static_cast<uint32_t>(log->strings.size()));
    log->strings.push_back(std::move(s));
    log->dict_offset = std::ftell(f);
  }
  std::fclose(f);
  return true;
}

// Columnar scan result; freed as one unit by pio_result_free.
struct ScanResult {
  uint64_t n = 0;
  double* event_time = nullptr;
  double* creation_time = nullptr;
  uint32_t* event = nullptr;
  uint32_t* entity_type = nullptr;
  uint32_t* entity_id = nullptr;
  int32_t* target_entity_type = nullptr;
  int32_t* target_entity_id = nullptr;
  // per-record varlen section: [u32 id_len][id][u32 blob_len][blob]
  uint8_t* varlen = nullptr;
  uint64_t varlen_len = 0;
};

struct Rec {
  uint8_t kind;
  double etime, ctime;
  uint32_t ev, ety, eid;
  int32_t tty, tid;
  const uint8_t* id;
  uint32_t id_len;
  const uint8_t* blob;
  uint32_t blob_len;
};

bool parse_record(const uint8_t* p, const uint8_t* end, Rec* r,
                  const uint8_t** next) {
  if (p + 4 > end) return false;
  uint32_t total;
  std::memcpy(&total, p, 4);
  const uint8_t* body = p + 4;
  if (body + total > end) return false;  // torn tail write — stop
  const uint8_t* q = body;
  r->kind = *q++;
  std::memcpy(&r->etime, q, 8); q += 8;
  std::memcpy(&r->ctime, q, 8); q += 8;
  std::memcpy(&r->ev, q, 4); q += 4;
  std::memcpy(&r->ety, q, 4); q += 4;
  std::memcpy(&r->eid, q, 4); q += 4;
  std::memcpy(&r->tty, q, 4); q += 4;
  std::memcpy(&r->tid, q, 4); q += 4;
  std::memcpy(&r->id_len, q, 4); q += 4;
  r->id = q; q += r->id_len;
  std::memcpy(&r->blob_len, q, 4); q += 4;
  r->blob = q;
  *next = body + total;
  return true;
}

}  // namespace

extern "C" {

void* pio_log_open(const char* dir) {
  Log* log = new Log();
  log->dir = dir;
  if (!load_dict(log)) { delete log; return nullptr; }
  log->log_file = std::fopen(log->log_path().c_str(), "ab");
  log->dict_file = std::fopen(log->dict_path().c_str(), "ab");
  if (log->log_file == nullptr || log->dict_file == nullptr) {
    if (log->log_file) std::fclose(log->log_file);
    if (log->dict_file) std::fclose(log->dict_file);
    delete log;
    return nullptr;
  }
  return log;
}

void pio_log_close(void* handle) {
  Log* log = static_cast<Log*>(handle);
  std::fclose(log->log_file);
  std::fclose(log->dict_file);
  delete log;
}

// Durability barrier: flush stdio buffers AND fsync to stable storage.
// Appends already fflush (kill -9 of the process loses nothing past
// the flush — the kernel owns the pages), so this call is only needed
// for power-loss durability; the Python wrapper gates it behind
// PIO_EVENTLOG_FSYNC as a batch commit (once per write-lock section,
// not per event). Returns 0 on success, -1 when any flush/fsync
// failed (EIO, volume full) — the wrapper surfaces that instead of
// acking a write that is not actually durable.
int pio_log_sync(void* handle) {
  Log* log = static_cast<Log*>(handle);
  int rc = 0;
  if (std::fflush(log->log_file) != 0) rc = -1;
  if (std::fflush(log->dict_file) != 0) rc = -1;
  if (PIO_FSYNC(PIO_FILENO(log->log_file)) != 0) rc = -1;
  if (PIO_FSYNC(PIO_FILENO(log->dict_file)) != 0) rc = -1;
  return rc;
}

// re-read dict entries appended by other processes (call under the
// cross-process write lock, or before decoding a fresh scan)
void pio_dict_reload(void* handle) {
  load_dict(static_cast<Log*>(handle));
}

// string → dict id (appending to the persistent dictionary when new)
uint32_t pio_intern(void* handle, const uint8_t* s, uint32_t len) {
  Log* log = static_cast<Log*>(handle);
  std::string key(reinterpret_cast<const char*>(s), len);
  auto it = log->dict.find(key);
  if (it != log->dict.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(log->strings.size());
  std::fwrite(&len, 4, 1, log->dict_file);
  std::fwrite(s, 1, len, log->dict_file);
  std::fflush(log->dict_file);
  log->dict.emplace(key, id);
  log->strings.push_back(std::move(key));
  log->dict_offset += 4 + static_cast<long>(len);
  return id;
}

uint64_t pio_dict_size(void* handle) {
  return static_cast<Log*>(handle)->strings.size();
}

// copy dict string `id` into out (returns its length; out may be null to size)
uint32_t pio_dict_get(void* handle, uint32_t id, uint8_t* out,
                      uint32_t out_cap) {
  Log* log = static_cast<Log*>(handle);
  if (id >= log->strings.size()) return 0;
  const std::string& s = log->strings[id];
  if (out != nullptr) {
    uint32_t n = s.size() < out_cap ? (uint32_t)s.size() : out_cap;
    std::memcpy(out, s.data(), n);
  }
  return static_cast<uint32_t>(s.size());
}

int pio_append(void* handle, uint8_t kind, double etime, double ctime,
               uint32_t ev, uint32_t ety, uint32_t eid, int32_t tty,
               int32_t tid, const uint8_t* id, uint32_t id_len,
               const uint8_t* blob, uint32_t blob_len) {
  Log* log = static_cast<Log*>(handle);
  uint32_t total = 1 + 8 + 8 + 4 * 5 + 4 + id_len + 4 + blob_len;
  std::vector<uint8_t> buf(4 + total);
  uint8_t* q = buf.data();
  std::memcpy(q, &total, 4); q += 4;
  *q++ = kind;
  std::memcpy(q, &etime, 8); q += 8;
  std::memcpy(q, &ctime, 8); q += 8;
  std::memcpy(q, &ev, 4); q += 4;
  std::memcpy(q, &ety, 4); q += 4;
  std::memcpy(q, &eid, 4); q += 4;
  std::memcpy(q, &tty, 4); q += 4;
  std::memcpy(q, &tid, 4); q += 4;
  std::memcpy(q, &id_len, 4); q += 4;
  std::memcpy(q, id, id_len); q += id_len;
  std::memcpy(q, &blob_len, 4); q += 4;
  std::memcpy(q, blob, blob_len);
  size_t written = std::fwrite(buf.data(), 1, buf.size(), log->log_file);
  if (written != buf.size()) return -1;
  std::fflush(log->log_file);
  return 0;
}

// Columnar scan. Filters: time range [t0, t1) with NaN = unbounded;
// ev_filter: array of allowed event ids (n_ev = 0 → any);
// ety/eid: -1 = any; tty/tid: -2 = any, -1 = must-be-absent, else match.
// Delete tombstones suppress matching event ids. include_varlen=0 skips
// copying ids/blobs (the pure-columnar fast path for training reads).
// id_filter (optional, len 0 = any): match one exact event id — the
// O(matching) path for get()/delete() instead of a full decode.
ScanResult* pio_scan(void* handle, double t0, double t1,
                     const uint32_t* ev_filter, uint32_t n_ev,
                     int64_t ety, int64_t eid, int64_t tty, int64_t tid,
                     int include_varlen, const uint8_t* id_filter,
                     uint32_t id_filter_len) {
  Log* log = static_cast<Log*>(handle);
  std::fflush(log->log_file);
  FILE* f = std::fopen(log->log_path().c_str(), "rb");
  ScanResult* res = new ScanResult();
  if (f == nullptr) return res;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> data(size);
  if (size > 0 && std::fread(data.data(), 1, size, f) != (size_t)size) {
    std::fclose(f);
    return res;
  }
  std::fclose(f);

  std::unordered_set<std::string> deleted;
  const uint8_t* p = data.data();
  const uint8_t* end = p + data.size();
  Rec r;
  const uint8_t* next;
  // pass 1: tombstones
  while (parse_record(p, end, &r, &next)) {
    if (r.kind == 2) {
      deleted.emplace(reinterpret_cast<const char*>(r.id), r.id_len);
    }
    p = next;
  }
  std::unordered_set<uint32_t> evs(ev_filter, ev_filter + n_ev);

  std::vector<double> etimes, ctimes;
  std::vector<uint32_t> evv, etyv, eidv;
  std::vector<int32_t> ttyv, tidv;
  std::vector<uint8_t> varlen;
  p = data.data();
  while (parse_record(p, end, &r, &next)) {
    p = next;
    if (r.kind != 1) continue;
    if (t0 == t0 && r.etime < t0) continue;  // t0==t0 ⇔ not NaN
    if (t1 == t1 && r.etime >= t1) continue;
    if (n_ev > 0 && evs.find(r.ev) == evs.end()) continue;
    if (ety >= 0 && r.ety != (uint32_t)ety) continue;
    if (eid >= 0 && r.eid != (uint32_t)eid) continue;
    if (tty == -1 && r.tty != -1) continue;
    if (tty >= 0 && r.tty != (int32_t)tty) continue;
    if (tid == -1 && r.tid != -1) continue;
    if (tid >= 0 && r.tid != (int32_t)tid) continue;
    if (id_filter_len > 0 &&
        (r.id_len != id_filter_len ||
         std::memcmp(r.id, id_filter, id_filter_len) != 0)) {
      continue;
    }
    if (!deleted.empty() &&
        deleted.count(std::string(
            reinterpret_cast<const char*>(r.id), r.id_len)) > 0) {
      continue;
    }
    etimes.push_back(r.etime);
    ctimes.push_back(r.ctime);
    evv.push_back(r.ev);
    etyv.push_back(r.ety);
    eidv.push_back(r.eid);
    ttyv.push_back(r.tty);
    tidv.push_back(r.tid);
    if (include_varlen != 0) {
      size_t off = varlen.size();
      varlen.resize(off + 4 + r.id_len + 4 + r.blob_len);
      uint8_t* q = varlen.data() + off;
      std::memcpy(q, &r.id_len, 4); q += 4;
      std::memcpy(q, r.id, r.id_len); q += r.id_len;
      std::memcpy(q, &r.blob_len, 4); q += 4;
      std::memcpy(q, r.blob, r.blob_len);
    }
  }

  res->n = etimes.size();
  auto copy = [](auto& vec) {
    using T = typename std::remove_reference<decltype(vec)>::type::value_type;
    T* out = static_cast<T*>(std::malloc(vec.size() * sizeof(T) + 1));
    std::memcpy(out, vec.data(), vec.size() * sizeof(T));
    return out;
  };
  res->event_time = copy(etimes);
  res->creation_time = copy(ctimes);
  res->event = copy(evv);
  res->entity_type = copy(etyv);
  res->entity_id = copy(eidv);
  res->target_entity_type = copy(ttyv);
  res->target_entity_id = copy(tidv);
  res->varlen = copy(varlen);
  res->varlen_len = varlen.size();
  return res;
}

uint64_t pio_result_n(ScanResult* r) { return r->n; }
double* pio_result_event_time(ScanResult* r) { return r->event_time; }
double* pio_result_creation_time(ScanResult* r) { return r->creation_time; }
uint32_t* pio_result_event(ScanResult* r) { return r->event; }
uint32_t* pio_result_entity_type(ScanResult* r) { return r->entity_type; }
uint32_t* pio_result_entity_id(ScanResult* r) { return r->entity_id; }
int32_t* pio_result_target_entity_type(ScanResult* r) {
  return r->target_entity_type;
}
int32_t* pio_result_target_entity_id(ScanResult* r) {
  return r->target_entity_id;
}
uint8_t* pio_result_varlen(ScanResult* r) { return r->varlen; }
uint64_t pio_result_varlen_len(ScanResult* r) { return r->varlen_len; }

void pio_result_free(ScanResult* r) {
  std::free(r->event_time);
  std::free(r->creation_time);
  std::free(r->event);
  std::free(r->entity_type);
  std::free(r->entity_id);
  std::free(r->target_entity_type);
  std::free(r->target_entity_id);
  std::free(r->varlen);
  delete r;
}

}  // extern "C"
