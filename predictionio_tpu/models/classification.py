"""Classification template — Naive Bayes over entity properties.

Capability parity with the reference
``examples/scala-parallel-classification`` (MLlib ``NaiveBayes.train``,
add-algorithm/src/main/scala/NaiveBayesAlgorithm.scala:15-28;
DataSource.scala reads ``$set`` entity properties): entities carry
numeric attribute properties plus a label property; train fits
multinomial NB; queries ``{"features": [...]}`` answer
``{"label": ..., "scores": {...}}``.

TPU path: the Preparator stages feature/label arrays padded + sharded
over the mesh data axis; training is a single jitted matmul-shaped fit
(:func:`predictionio_tpu.ops.naive_bayes.fit_multinomial`); serving
dispatches one pre-compiled fixed-shape scoring program.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    register_engine,
)
from predictionio_tpu.core.controller import SanityCheck
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.ops import naive_bayes as nb
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.utils.bimap import BiMap

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ClassificationDataSourceParams(Params):
    app_name: str = "MyApp"
    entity_type: str = "user"
    attributes: tuple[str, ...] = ("attr0", "attr1", "attr2")
    label: str = "plan"
    eval_k: int = 0  # >0 enables k-fold read_eval


@dataclasses.dataclass
class ClassificationTrainingData(SanityCheck):
    x: np.ndarray            # [n, d] float32
    y: np.ndarray            # [n] int32 label codes
    label_map: BiMap

    def sanity_check(self) -> None:
        if len(self.x) == 0:
            raise ValueError("training data is empty")
        if not np.isfinite(self.x).all():
            raise ValueError("training features contain NaN/inf")
        if (self.x < 0).any():
            raise ValueError(
                "multinomial NB requires non-negative features"
            )


class ClassificationDataSource(
    DataSource[ClassificationTrainingData, dict, dict, str]
):
    params_class = ClassificationDataSourceParams

    def _read(self) -> ClassificationTrainingData:
        p = self.params
        props = EventStore().aggregate_properties(
            p.app_name,
            entity_type=p.entity_type,
            required=list(p.attributes) + [p.label],
        )
        rows, labels = [], []
        for _eid, pm in props.items():
            rows.append([pm.get_float(a) for a in p.attributes])
            labels.append(str(pm.get_required(p.label)))
        label_map, y = BiMap.string_int_with_codes(
            np.asarray(labels, dtype=np.str_)
        ) if labels else (BiMap(np.asarray([], dtype=np.str_)),
                          np.zeros(0, np.int32))
        return ClassificationTrainingData(
            x=np.asarray(rows, dtype=np.float32).reshape(
                len(rows), len(p.attributes)
            ),
            y=y,
            label_map=label_map,
        )

    def read_training(self, ctx: ComputeContext) -> ClassificationTrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        """k-fold split (shared :func:`~predictionio_tpu.core.evaluation
        .kfold_indices`)."""
        from predictionio_tpu.core.evaluation import kfold_indices

        full = self._read()
        folds = []
        for fold, train_idx, test_idx in kfold_indices(
            len(full.x), self.params.eval_k
        ):
            td = ClassificationTrainingData(
                x=full.x[train_idx], y=full.y[train_idx],
                label_map=full.label_map,
            )
            qa = [
                (
                    {"features": full.x[i].tolist()},
                    full.label_map.inverse(int(full.y[i])),
                )
                for i in test_idx
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


@dataclasses.dataclass
class PreparedClassificationData:
    x: jax.Array      # [n_pad, d] data-sharded
    y: jax.Array      # [n_pad]
    mask: jax.Array   # [n_pad] 1.0 real / 0.0 padding
    label_map: BiMap
    n_classes: int


class ClassificationPreparator(
    Preparator[ClassificationTrainingData, PreparedClassificationData]
):
    """Fixed-shape boundary: pad rows to the data-axis multiple and place
    on the mesh (SURVEY.md §7 hard-part (a))."""

    def prepare(
        self, ctx: ComputeContext, td: ClassificationTrainingData
    ) -> PreparedClassificationData:
        return PreparedClassificationData(
            x=ctx.shard_rows(td.x),
            y=ctx.shard_rows(td.y),
            mask=ctx.shard_rows(np.ones(len(td.x), np.float32)),
            label_map=td.label_map,
            n_classes=max(len(td.label_map), 1),
        )


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0


@dataclasses.dataclass
class NaiveBayesModel:
    nb: nb.MultinomialNBModel
    label_map: BiMap


class NaiveBayesAlgorithm(
    Algorithm[PreparedClassificationData, NaiveBayesModel, dict, dict]
):
    """Reference NaiveBayesAlgorithm.scala:15-28 (MLlib NB, lambda)."""

    params_class = NaiveBayesParams

    def train(
        self, ctx: ComputeContext, pd: PreparedClassificationData
    ) -> NaiveBayesModel:
        model = nb.fit_multinomial(
            pd.x,
            pd.y,
            n_classes=pd.n_classes,
            alpha=self.params.lambda_,
            mask=pd.mask,
        )
        return NaiveBayesModel(nb=model, label_map=pd.label_map)

    def predict(self, model: NaiveBayesModel, query: dict) -> dict:
        x = jnp.asarray(
            [query["features"]], dtype=model.nb.theta.dtype
        )
        scores = nb.log_scores(model.nb, x)[0]
        best = int(jnp.argmax(scores))
        return {
            "label": model.label_map.inverse(best),
            "scores": {
                model.label_map.inverse(c): float(scores[c])
                for c in range(model.nb.n_classes)
            },
        }

    def batch_predict(self, model: NaiveBayesModel, queries) -> list[dict]:
        if not queries:
            return []
        return self.batch_predict_collect(
            model, self.batch_predict_launch(model, queries), queries
        )

    def batch_predict_launch(self, model: NaiveBayesModel, queries):
        """Two-phase serving: upload features + enqueue the jitted
        scorer, return the un-fetched class indices."""
        if not queries:
            return None
        x = jnp.asarray(
            [q["features"] for q in queries], dtype=model.nb.theta.dtype
        )
        return nb.predict_classes(model.nb, x)

    def batch_predict_collect(
        self, model: NaiveBayesModel, handle, queries
    ) -> list[dict]:
        if handle is None:
            return []
        best = np.asarray(handle)  # the device barrier
        return [
            {"label": model.label_map.inverse(int(b))} for b in best
        ]


def classification_engine() -> Engine:
    return Engine(
        ClassificationDataSource,
        ClassificationPreparator,
        {"naive": NaiveBayesAlgorithm},
        FirstServing,
    )


register_engine("classification", classification_engine)
