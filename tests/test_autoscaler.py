"""Replica autoscaler (serving/autoscaler.py): reconciliation policy
against a scripted router, spawner argv/banner mechanics, and the
slot-ownership discipline that keeps the shared supervisor and the
router's sticky drain from fighting over one process."""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.serving.autoscaler import (
    AutoscalerConfig,
    ReplicaAutoscaler,
    ReplicaSpawner,
    SpawnError,
)


class FakeProc:
    _pid = 5000

    def __init__(self):
        FakeProc._pid += 1
        self.pid = FakeProc._pid
        self.terminated = False

    def poll(self):
        return None

    def terminate(self):
        self.terminated = True


class FakeLaunchSpawner:
    """Duck-typed ReplicaSpawner: records launches, never forks."""

    def __init__(self):
        self.launches: list[tuple[str, int]] = []
        self._port = 9000

    def launch(self, generation, port=0):
        self.launches.append((generation, port))
        if port == 0:
            self._port += 1
            port = self._port
        return FakeProc(), port


class FakeReplicaEntry:
    def __init__(self, replica_id, generation, staged):
        self.replica_id = replica_id
        self.generation = generation
        self.staged = staged


class ScriptedRouter:
    """The router surface the autoscaler reconciles against."""

    def __init__(self):
        self.signals = {
            "healthy": 0,
            "warming": 0,
            "draining": 0,
            "unhealthy": 0,
            "inflight": 0,
            "saturated": 0,
            "shedTotal": 0,
            "swapActive": False,
            "servingGeneration": "g1",
        }
        self.states: dict[str, str] = {}
        self.added: list[FakeReplicaEntry] = []
        self.retired: list[str] = []
        self.spawner = None
        self.status_fn = None

    def attach_spawner(self, fn):
        self.spawner = fn

    def attach_autoscaler_status(self, fn):
        self.status_fn = fn

    def autoscaler_signals(self):
        return dict(self.signals)

    def replica_states(self):
        return dict(self.states)

    def add_replica(self, url, replica_id=None, generation="",
                    pid=None, staged=False):
        entry = FakeReplicaEntry(replica_id, generation, staged)
        self.added.append(entry)
        self.states[replica_id] = "healthy"
        return entry

    def retire(self, replica_id, wait=False):
        if replica_id not in self.states:
            return False
        self.states.pop(replica_id)
        self.retired.append(replica_id)
        return True

    def update_replica_pid(self, replica_id, pid):
        return replica_id in self.states


def make_scaler(router=None, **config_kw):
    router = router or ScriptedRouter()
    config_kw.setdefault("min_replicas", 1)
    config_kw.setdefault("max_replicas", 4)
    config_kw.setdefault("shrink_after_ticks", 2)
    scaler = ReplicaAutoscaler(
        router,
        FakeLaunchSpawner(),
        config=AutoscalerConfig(**config_kw),
        registry=MetricRegistry(),
    )
    return router, scaler


class TestReconcilePolicy:
    def test_shed_grows_the_pool(self):
        router, scaler = make_scaler()
        router.signals.update(healthy=1, shedTotal=3)
        assert scaler.reconcile_once() == "grow"
        assert scaler.target == 2
        assert [e.generation for e in router.added] == ["g1"]
        assert not router.added[0].staged

    def test_saturation_majority_grows_before_sheds(self):
        router, scaler = make_scaler(saturation_fraction=0.5)
        router.signals.update(healthy=2, saturated=1)
        assert scaler.reconcile_once() == "grow"
        assert scaler.target == 3  # max(target, actual=2) + 1

    def test_growth_gates_on_current_warmup(self):
        """One replica at a time: while a spawn is still warming, the
        loop holds even under continued pressure."""
        router, scaler = make_scaler()
        router.signals.update(healthy=1, shedTotal=1)
        assert scaler.reconcile_once() == "grow"
        router.signals.update(healthy=1, warming=1, shedTotal=2)
        scaler.target = 4
        assert scaler.reconcile_once() == "idle"
        assert len(router.added) == 1

    def test_grow_deferred_while_generation_ambiguous(self):
        """A mixed-generation pool with no explicit serving generation
        (an ungated roll in flight) gives the spawn template an empty
        generation — growing then would launch a wrong/default-model
        replica into live selection. The loop defers instead."""
        router, scaler = make_scaler()
        router.signals.update(
            healthy=1, shedTotal=3,
            servingGeneration="", generationAmbiguous=True,
        )
        assert scaler.reconcile_once() == "idle"
        assert router.added == []
        # the roll converges: growth resumes at the settled generation
        router.signals.update(
            shedTotal=4, servingGeneration="g2",
            generationAmbiguous=False,
        )
        assert scaler.reconcile_once() == "grow"
        assert [e.generation for e in router.added] == ["g2"]

    def test_shed_delta_not_absolute(self):
        """A historical shed total must not grow the pool forever —
        only NEW sheds since the last tick count."""
        router, scaler = make_scaler()
        router.signals.update(healthy=1, shedTotal=5)
        assert scaler.reconcile_once() == "grow"
        router.signals.update(healthy=2, warming=0, shedTotal=5)
        assert scaler.reconcile_once() == "idle"
        assert scaler.target == 2

    def test_sustained_low_utilization_shrinks_losslessly(self):
        router, scaler = make_scaler(
            shrink_after_ticks=2, low_inflight_per_replica=0.5
        )
        # grow to 2 owned replicas first
        router.signals.update(healthy=1, shedTotal=1)
        scaler.reconcile_once()
        router.signals.update(healthy=2, shedTotal=1, inflight=0)
        assert scaler.reconcile_once() == "idle"  # low tick 1
        action = scaler.reconcile_once()          # low tick 2 -> shrink
        assert action == "shrink"
        assert scaler.target == 1
        # the newest owned replica retired through the router's sticky
        # drain, and its slot stopped being supervised FIRST
        assert router.retired == ["as-1"]
        assert all(s.retired for s in scaler._slots)

    def test_one_low_tick_is_not_enough(self):
        router, scaler = make_scaler(shrink_after_ticks=3)
        router.signals.update(healthy=1, shedTotal=1)
        scaler.reconcile_once()
        router.signals.update(healthy=2, inflight=0, shedTotal=1)
        assert scaler.reconcile_once() == "idle"
        # load returns: the shrink counter resets
        router.signals.update(inflight=4)
        scaler.reconcile_once()
        assert scaler._low_ticks == 0

    def test_swap_active_pauses_scaling_but_tops_up(self):
        router, scaler = make_scaler()
        scaler.target = 2
        router.signals.update(
            healthy=1, swapActive=True, shedTotal=9, inflight=0
        )
        assert scaler.reconcile_once() == "grow"  # top-up only
        assert scaler.target == 2  # sheds did NOT raise the target
        router.signals.update(healthy=2, swapActive=True)
        assert scaler.reconcile_once() == "idle"  # and never shrinks

    def test_prune_releases_externally_retired_replicas(self):
        """A fleet swap rolling the old generation retires replicas
        the autoscaler owns: their slots must stop respawning the
        drained processes."""
        router, scaler = make_scaler()
        router.signals.update(healthy=1, shedTotal=1)
        scaler.reconcile_once()
        slot1 = scaler._owned["as-1"]
        router.states.pop("as-1")  # swap drained it
        router.signals.update(healthy=1, shedTotal=1, warming=0)
        scaler.reconcile_once()
        assert "as-1" not in scaler._owned
        assert slot1.retired

    def test_spawn_skips_ids_adopted_by_restarted_router(self):
        """A restarted router re-adopts ``as-N`` replicas from its
        state file while a FRESH autoscaler's counter restarts at 1:
        the allocator must skip the adopted ids instead of colliding
        (add_replica raises on a duplicate id, wasting the launched
        process)."""
        router = ScriptedRouter()
        # the state file brought back two autoscaler-named replicas
        router.states.update({"as-1": "healthy", "as-2": "healthy"})
        router, scaler = make_scaler(router)
        router.signals.update(healthy=2, shedTotal=3)
        assert scaler.reconcile_once() == "grow"
        assert [e.replica_id for e in router.added] == ["as-3"]
        router, scaler = make_scaler()
        replica = router.spawner("g2", True)
        assert replica.staged and replica.generation == "g2"
        assert replica.replica_id in scaler._owned

    def test_target_clamped_to_bounds(self):
        router, scaler = make_scaler(min_replicas=2, max_replicas=3)
        assert scaler.target == 2
        router.signals.update(healthy=3, saturated=3, shedTotal=1)
        scaler.reconcile_once()
        scaler.reconcile_once()
        assert scaler.target == 3

    def test_status_surface(self):
        router, scaler = make_scaler()
        status = router.status_fn()
        assert status["target"] == scaler.target
        assert status["min"] == 1 and status["max"] == 4


class TestReplicaSpawner:
    def test_argv_substitution(self):
        spawner = ReplicaSpawner(
            ["python", "child.py", "--port", "{port}",
             "--generation", "{generation}"]
        )
        assert spawner.argv("g7", 8123) == [
            "python", "child.py", "--port", "8123",
            "--generation", "g7",
        ]

    def test_empty_template_rejected(self):
        with pytest.raises(ValueError):
            ReplicaSpawner([])

    def test_launch_parses_banner_port(self):
        script = textwrap.dedent(
            """
            import sys, time
            print("x listening on 127.0.0.1:4321 pid=9", flush=True)
            time.sleep(30)
            """
        )
        spawner = ReplicaSpawner(
            [sys.executable, "-c", script], spawn_timeout_s=30
        )
        proc, port = spawner.launch("g1", port=0)
        try:
            assert port == 4321
        finally:
            proc.kill()
            proc.wait(timeout=10)

    def test_launch_explicit_port_skips_banner(self):
        spawner = ReplicaSpawner(
            [sys.executable, "-c", "import time; time.sleep(30)"]
        )
        proc, port = spawner.launch("g1", port=7777)
        try:
            assert port == 7777
        finally:
            proc.kill()
            proc.wait(timeout=10)

    def test_dead_child_raises_spawn_error(self):
        spawner = ReplicaSpawner(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            spawn_timeout_s=30,
        )
        with pytest.raises(SpawnError, match="rc=3"):
            spawner.launch("g1", port=0)

    def test_bannerless_child_times_out(self):
        spawner = ReplicaSpawner(
            [sys.executable, "-c",
             "import time; print('no banner'); time.sleep(30)"],
            spawn_timeout_s=0.5,
        )
        with pytest.raises(SpawnError, match="never printed"):
            spawner.launch("g1", port=0)


class TestConfig:
    def test_from_env_defaults(self, monkeypatch):
        for k in list(dict(**__import__("os").environ)):
            if k.startswith("PIO_AUTOSCALE"):
                monkeypatch.delenv(k, raising=False)
        cfg = AutoscalerConfig.from_env()
        assert cfg.min_replicas == 1 and cfg.max_replicas == 4

    def test_from_env_overrides(self, monkeypatch):
        monkeypatch.setenv("PIO_AUTOSCALE_MIN", "2")
        monkeypatch.setenv("PIO_AUTOSCALE_MAX", "8")
        monkeypatch.setenv("PIO_AUTOSCALE_SHRINK_TICKS", "3")
        cfg = AutoscalerConfig.from_env()
        assert cfg.min_replicas == 2
        assert cfg.max_replicas == 8
        assert cfg.shrink_after_ticks == 3


def test_fake_proc_infra():
    """The FakeProc pid counter must keep fixtures distinguishable."""
    assert FakeProc().pid != FakeProc().pid


def _unused(*_a):  # keep subprocess import honest for linters
    return subprocess


class TestConcurrentBookkeeping:
    """PR 12 regression: ``_owned``/``_slots`` are guarded by
    ``_lock`` — the reconcile thread's shrink/prune scans must not
    fight the swap thread's spawn insertions (pre-fix, the unlocked
    dict scan could raise ``RuntimeError: dictionary changed size
    during iteration`` or pop a slot the scan never saw)."""

    def test_swap_spawn_concurrent_with_shrink_and_prune(self):
        import threading

        router, scaler = make_scaler(max_replicas=64)
        errors: list[BaseException] = []
        stop = threading.Event()

        def swap_spawner():
            try:
                while not stop.is_set():
                    scaler.spawn_for_swap("g2", staged=False)
            except BaseException as e:  # noqa: BLE001 - fail the test
                errors.append(e)

        def reconciler():
            try:
                while not stop.is_set():
                    scaler._shrink()
                    scaler._prune_retired()
            except BaseException as e:  # noqa: BLE001 - fail the test
                errors.append(e)

        threads = [
            threading.Thread(target=swap_spawner, daemon=True),
            threading.Thread(target=reconciler, daemon=True),
        ]
        [t.start() for t in threads]
        import time

        time.sleep(0.4)
        stop.set()
        [t.join(timeout=5) for t in threads]
        assert errors == []
        # bookkeeping converged: every owned replica is either still
        # registered with the router or was popped before its retire
        states = router.replica_states()
        for rid in list(scaler._owned):
            assert rid in states or rid in router.retired
