"""Multi-host launch boundary tests (VERDICT r1 #7): the process
launcher must coordinate a real 2-process jax.distributed job
(reference Runner.runOnSpark, tools/Runner.scala:92-210 — `local[4]`
threads never crossed a process boundary; this does)."""

import os
import subprocess
import sys

from predictionio_tpu.parallel.distributed import launch_processes

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


class TestLaunchProcesses:
    def test_two_process_distributed_pjit_job(self):
        """Two coordinated processes run a global-mesh pjit reduction."""
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        rc = launch_processes(
            [sys.executable, os.path.join(_HERE, "distributed_child.py")],
            num_processes=2,
            env=env,
            timeout=180,
        )
        assert rc == 0

    def _run_sharded_als(
        self, nprocs: int, local_devices: int, mesh: str, timeout: int
    ) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["PIO_TEST_NPROCS"] = str(nprocs)
        env["PIO_TEST_LOCAL_DEVICES"] = str(local_devices)
        env["PIO_TEST_MESH"] = mesh
        rc = launch_processes(
            [
                sys.executable,
                os.path.join(_HERE, "distributed_als_child.py"),
            ],
            num_processes=nprocs,
            env=env,
            timeout=timeout,
        )
        assert rc == 0

    def test_two_process_sharded_als_train(self):
        """The REAL training path across the process boundary: model-
        sharded ALS (shard_map + all-gathers) on a 2-host × 2-device
        mesh matches a single-process run of the same problem."""
        self._run_sharded_als(2, 2, "2x2", timeout=300)

    def test_four_process_model4_sharded_als(self):
        """4 hosts × 2 devices, model axis 4: every all-gather group
        spans two process boundaries; factors must still match the
        single-process reference and stay genuinely sharded."""
        self._run_sharded_als(4, 2, "2x4", timeout=420)

    def test_eight_process_model8_sharded_als(self):
        """8 single-device hosts, model axis 8 — the maximal topology
        this sandbox can express: all-gather reassembly and the
        plan_shards inverse permutation have the most ways to be wrong
        here."""
        self._run_sharded_als(8, 1, "1x8", timeout=600)

    def test_env_contract(self):
        """Children see coordinator address, world size, and their rank."""
        probe = (
            "import os,sys;"
            "assert os.environ['PIO_NUM_PROCESSES']=='2';"
            "assert os.environ['PIO_COORDINATOR_ADDRESS'];"
            "sys.exit(int(os.environ['PIO_PROCESS_ID']))"
        )
        # ranks 0 and 1 exit with their rank: first nonzero rc is 1
        rc = launch_processes(
            [sys.executable, "-c", probe], num_processes=2, timeout=60
        )
        assert rc == 1

    def test_failure_propagates_and_terminates(self):
        rc = launch_processes(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            num_processes=2,
            timeout=60,
        )
        assert rc == 3

    def test_cli_launch_verb(self):
        """`pio-tpu launch -n 2 -- <cmd>` sets the contract env."""
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [
                sys.executable, "-m", "predictionio_tpu.cli.main",
                "launch", "-n", "2", "--",
                sys.executable, "-c",
                # single write: two children share the pipe, and a
                # print() may issue multiple write() calls that interleave
                "import os,sys;"
                "sys.stdout.write('rank %s\\n' % os.environ['PIO_PROCESS_ID'])",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        ranks = sorted(
            line for line in out.stdout.splitlines() if "rank" in line
        )
        assert ranks == ["rank 0", "rank 1"]
