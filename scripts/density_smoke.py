"""Multi-tenant pool smoke: pooled replicas survive kill -9 and LRU
eviction racing in-flight queries without losing a request.

Topology: two REAL pooled multi-tenant engine-server replicas
(tests/pool_replica_child.py — three tenants through a ModelPool whose
byte budget fits ~ONE tenant table, so every tenant alternation evicts)
behind an in-process ServingRouter with tenant-keyed affinity. The
script proves, in order:

1. tenant routing end-to-end: accessKey-keyed queries answer with the
   RIGHT tenant's model through the router, and the replicas' pool
   metrics show evictions happening WHILE traffic flows — the
   eviction-vs-in-flight-query race runs continuously and loses
   nothing (pins hold the serving generation until the query drains);
2. SIGKILL of one pooled replica mid-traffic: the tenant-keyed ring
   fails the dead replica's tenants over to the survivor (which cold-
   faults them into its own pool), the worker supervisor respawns the
   victim, and the victim is readmitted once its tenants preload —
   zero non-200s end to end;
3. per-tenant /reload through the router path: one tenant's generation
   advances on one replica, other tenants keep serving.

Run by ``scripts/check.sh`` next to router_smoke.py.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PIO_BREAKER_FAILURES"] = "2"
os.environ["PIO_BREAKER_RESET_S"] = "0.5"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # the package itself (no install required)

from predictionio_tpu.serving import workers  # noqa: E402
from predictionio_tpu.serving.config import ServerConfig  # noqa: E402
from predictionio_tpu.serving.router import ServingRouter  # noqa: E402

ADMIN_KEY = "density-smoke-key"
CHILD = os.path.join(REPO, "tests", "pool_replica_child.py")
#: tenant → expected algo id (pool_replica_child.ALGO_IDS via TENANTS)
TENANT_ALGO = {"alice": 1, "bob": 2, "carol": 3}

failures: list[str] = []


def check(cond: bool, label: str) -> None:
    print(("ok   " if cond else "FAIL ") + label, flush=True)
    if not cond:
        failures.append(label)


def http_json(url, body=None, headers=None, timeout=20, method=None):
    """(status, parsed body); no raise on 4xx/5xx."""
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        method=method or ("POST" if body is not None else "GET"),
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def spawn_replica(name: str, port: int = 0) -> tuple:
    """(proc, port): a pooled replica child, banner-parsed."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    proc = subprocess.Popen(
        [sys.executable, CHILD, "--port", str(port),
         "--generation", name, "--delay-ms", "5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    bound: list[int] = []

    def _scan():
        for line in proc.stdout:
            if "listening on" in line and not bound:
                bound.append(
                    int(line.split("pid=")[0].rsplit(":", 1)[1])
                )
        # keep draining so request logs can't block the child

    threading.Thread(target=_scan, daemon=True).start()
    deadline = time.monotonic() + 120
    while not bound and time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"replica {name} died at startup")
        time.sleep(0.1)
    if not bound:
        proc.kill()
        raise RuntimeError(f"replica {name} never printed its port")
    return proc, bound[0]


def wait_states(base: str, want: dict, deadline_s: float = 120) -> bool:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        _, status = http_json(f"{base}/")
        states = {
            r["id"]: r["state"] for r in status.get("replicas", [])
        }
        if all(states.get(rid) == s for rid, s in want.items()):
            return True
        time.sleep(0.2)
    return False


def pool_evictions(replica_base: str) -> int:
    """Sum of pio_pool_evictions_total across tenants on one replica."""
    try:
        _, data = http_json(f"{replica_base}/metrics.json", timeout=5)
    except OSError:
        return 0
    samples = data.get("pio_pool_evictions_total", {}).get(
        "samples", ()
    )
    return int(
        sum(s.get("value", s.get("count", 0)) for s in samples)
    )


def metric_value(base: str, name: str, **labels):
    _, data = http_json(f"{base}/metrics.json")
    if "federation" in data:
        data = data.get("local", {})
    for sample in data.get(name, {}).get("samples", ()):
        if all(
            sample["labels"].get(k) == v for k, v in labels.items()
        ):
            return sample.get("value", sample.get("count"))
    return None


class Traffic:
    """Closed-loop tenant-keyed query generators; every outcome is
    recorded with the tenant that issued it so answers are provable."""

    def __init__(self, base: str, threads: int = 3):
        self.base = base
        self.stop = threading.Event()
        self.outcomes: list[tuple[str, int, dict | None]] = []
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(threads)
        ]

    def _run(self, seed: int) -> None:
        tenants = list(TENANT_ALGO)
        i = seed
        while not self.stop.is_set():
            i += 1
            tenant = tenants[i % len(tenants)]
            try:
                status, body = http_json(
                    f"{self.base}/queries.json?accessKey={tenant}",
                    {"x": i % 100},
                    headers={"X-PIO-Deadline": "15000"},
                    timeout=20,
                )
            except OSError as e:
                status, body = -1, {"error": str(e)}
            with self._lock:
                self.outcomes.append((tenant, status, body))

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def finish(self) -> list:
        self.stop.set()
        for t in self._threads:
            t.join(timeout=30)
        with self._lock:
            return list(self.outcomes)


def wrong_answers(outcomes) -> list:
    """Outcomes whose status or tenant-model pairing is wrong."""
    bad = []
    for tenant, status, body in outcomes:
        if status != 200:
            bad.append((tenant, status, body))
            continue
        expected = TENANT_ALGO[tenant] * 1000
        result = (body or {}).get("result", -1)
        if result // 1000 * 1000 != expected:
            bad.append((tenant, status, body))
    return bad


def spawn_and_adopt(name: str, port: int, procs: dict):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    proc = subprocess.Popen(
        [sys.executable, CHILD, "--port", str(port),
         "--generation", "a2", "--delay-ms", "5"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs[name] = proc
    return proc


def main() -> int:
    procs: dict[str, subprocess.Popen] = {}
    stopping = threading.Event()
    router = None
    http = None
    try:
        print("starting 2 pooled multi-tenant replicas...", flush=True)
        proc_a, port_a = spawn_replica("a1")
        proc_b, port_b = spawn_replica("b1")
        procs["a"], procs["b"] = proc_a, proc_b
        rep_a = f"http://127.0.0.1:{port_a}"
        rep_b = f"http://127.0.0.1:{port_b}"

        config = ServerConfig(
            key_auth_enforced=True, access_key=ADMIN_KEY
        )
        router = ServingRouter(
            probe_interval_s=0.2,
            probe_timeout_s=2.0,
            unhealthy_after=1,
            failover_retries=1,
            proxy_timeout_s=20.0,
            server_config=config,
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        key_hdr = {"X-PIO-Server-Key": ADMIN_KEY}
        for rid, url in (("a", rep_a), ("b", rep_b)):
            status, _ = http_json(
                f"{base}/admin/replicas",
                {"id": rid, "url": url, "generation": "g1"},
                headers=key_hdr,
            )
            check(status == 201, f"replica {rid} registered")
        check(
            wait_states(base, {"a": "healthy", "b": "healthy"}),
            "both pooled replicas admitted after tenant preload",
        )

        # -- 1: eviction races in-flight queries, losslessly ----------
        ev_before = pool_evictions(rep_a) + pool_evictions(rep_b)
        traffic = Traffic(base).start()
        time.sleep(3.0)
        outcomes = traffic.finish()
        bad = wrong_answers(outcomes)
        check(
            len(outcomes) > 10,
            f"tenant traffic flowed ({len(outcomes)} requests; "
            "most fault a cold tenant stage, which is the point)",
        )
        check(
            not bad,
            f"all {len(outcomes)} tenant-keyed answers correct "
            f"(bad={bad[:3]})",
        )
        ev_during = (
            pool_evictions(rep_a) + pool_evictions(rep_b) - ev_before
        )
        check(
            ev_during > 0,
            f"pool evicted WHILE traffic flowed ({ev_during} "
            "evictions) — the eviction/in-flight race ran",
        )

        # -- 2: SIGKILL a pooled replica mid-traffic -------------------
        slot = workers.WorkerSlot(
            lambda: spawn_and_adopt("a-respawn", port_a, procs),
            proc=proc_a,
        )
        supervisor = threading.Thread(
            target=workers.supervise_children,
            args=([slot], stopping),
            kwargs={"poll_interval_s": 0.2},
            daemon=True,
        )
        supervisor.start()
        traffic = Traffic(base).start()
        time.sleep(1.5)
        print(f"SIGKILL pooled replica a (pid {proc_a.pid})", flush=True)
        os.kill(proc_a.pid, signal.SIGKILL)
        time.sleep(4.0)  # traffic rides through the outage + respawn
        outcomes = traffic.finish()
        bad = wrong_answers(outcomes)
        check(
            len(outcomes) > 10,
            f"traffic flowed through the kill ({len(outcomes)})",
        )
        check(
            not bad,
            f"zero lost/wrong answers through SIGKILL "
            f"({len(outcomes)} requests, bad={bad[:3]})",
        )
        failovers = metric_value(base, "pio_router_failovers_total")
        check(
            (failovers or 0) > 0,
            f"pio_router_failovers_total > 0 (={failovers})",
        )
        check(
            wait_states(base, {"a": "healthy"}, deadline_s=120),
            "killed pooled replica respawned and readmitted once its "
            "tenants preloaded",
        )
        stopping.set()
        supervisor.join(timeout=5)

        # -- 3: per-tenant reload keeps the other tenants serving ------
        status, body = http_json(
            f"{rep_b}/reload", {"tenant": "bob"}
        )
        check(
            status == 200 and body.get("generation", 0) >= 2,
            f"per-tenant reload advanced bob's generation ({body})",
        )
        status, body = http_json(
            f"{rep_b}/queries.json?accessKey=alice", {"x": 3}
        )
        check(
            status == 200 and body["result"] == 1003,
            "alice unaffected by bob's reload",
        )
        _, rep_status = http_json(f"{rep_b}/")
        check(
            rep_status.get("multiTenant") is True
            and rep_status.get("pool", {}).get("budgetBytes", 0) > 0,
            "replica status reports the pool "
            f"(pool={rep_status.get('pool')})",
        )
    finally:
        stopping.set()
        if http is not None:
            http.shutdown()
        if router is not None:
            router.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()

    if failures:
        print(
            f"density_smoke: FAILED ({len(failures)}): "
            + "; ".join(failures),
            file=sys.stderr,
        )
        return 1
    print("density_smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
