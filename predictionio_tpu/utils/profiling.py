"""Tracing / profiling subsystem.

The reference has no profiler beyond per-request latency counters and
the Spark UI (SURVEY.md §5 "Tracing / profiling"); the TPU build makes
this first-class:

* :class:`StepTimer` — per-step wall-clock records for training loops
  (ALS logs one record per alternating solve), queryable and
  JSON-serializable for run metadata.
* :func:`trace` — context manager around ``jax.profiler`` producing a
  Perfetto/TensorBoard trace when a directory is given (or the
  ``PIO_TRACE_DIR`` env var is set); no-op otherwise.

Timing always syncs through a device→host fetch — ``block_until_ready``
alone is not a reliable barrier on every platform (see bench.py).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import defaultdict

import jax

from predictionio_tpu.obs import tracing

logger = logging.getLogger(__name__)


def sync(value) -> None:
    """Reliable device barrier: fetch a scalar reduction to host."""
    if isinstance(value, jax.Array):
        jax.device_get(value.ravel()[0] if value.size else value)


class StepTimer:
    """Named per-step wall-clock records."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.records: dict[str, list[float]] = defaultdict(list)

    @contextlib.contextmanager
    def step(self, name: str, sync_value=None):
        # each step is also a tracing span (no-op outside an open
        # trace), so `pio train` emits the same Perfetto timeline the
        # serving stack does
        if not self.enabled:
            yield
            return
        with tracing.span(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                if sync_value is not None:
                    sync(sync_value)
                self.records[name].append(time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        if self.enabled:
            self.records[name].append(seconds)

    def summary(self) -> dict[str, dict[str, float]]:
        out = {}
        for name, xs in self.records.items():
            out[name] = {
                "count": len(xs),
                "total_s": round(sum(xs), 6),
                "mean_s": round(sum(xs) / len(xs), 6),
                "max_s": round(max(xs), 6),
            }
        return out

    def to_json(self) -> str:
        return json.dumps(self.summary())

    def publish(self, registry, name: str = "pio_train_step_seconds"):
        """Fold the records into a shared metric registry
        (:class:`~predictionio_tpu.obs.MetricRegistry`) as a per-step
        labeled histogram — the bridge that makes train-time timing
        scrapeable from the same ``/metrics`` surface as serving."""
        from predictionio_tpu.obs import TRAIN_STEP_BUCKETS

        hist = registry.histogram(
            name,
            "Training-loop step wall clock (StepTimer records)",
            ("step",),
            buckets=TRAIN_STEP_BUCKETS,
        )
        for step, xs in self.records.items():
            child = hist.labels(step)
            for seconds in xs:
                child.observe(seconds)
        return hist

    def log_summary(self, prefix: str = "") -> None:
        for name, s in self.summary().items():
            logger.info(
                "%s%s: %d step(s), mean %.4fs, total %.2fs",
                prefix,
                name,
                s["count"],
                s["mean_s"],
                s["total_s"],
            )


@contextlib.contextmanager
def trace(trace_dir: str | None = None):
    """JAX profiler trace (Perfetto/TensorBoard) when a dir is given or
    PIO_TRACE_DIR is set; transparent otherwise."""
    trace_dir = trace_dir or os.environ.get("PIO_TRACE_DIR")
    if not trace_dir:
        yield
        return
    os.makedirs(trace_dir, exist_ok=True)
    logger.info("writing profiler trace to %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield
