"""Resilience primitives for the serving tier.

At the ROADMAP's scale ("heavy traffic from millions of users") partial
failure is the steady state, not the exception: a store server restarts
mid-deploy, a network hiccup eats a keep-alive socket, a slow device
dispatch outlives the client that asked for it. The serving tier is a
chain of HTTP hops (client → engine, engine → store, event → store) and
every hop used to have exactly one defense: a fixed socket timeout.
This module gives the chain four coordinated behaviors, used by
:mod:`~predictionio_tpu.serving.http`, :mod:`~predictionio_tpu.client`,
:mod:`~predictionio_tpu.data.storage.httpstore`, and
:mod:`~predictionio_tpu.serving.batching`:

* **Deadline propagation** — a request carries its remaining time
  budget in the ``X-PIO-Deadline`` header (milliseconds). Each server
  rejects already-expired work at admission (504, before any handler
  runs), installs the deadline in a contextvar, and every outbound hop
  re-mints the header from what is left, so the budget shrinks across
  the chain instead of resetting. The micro-batcher drops expired
  slots *before* device dispatch — no computing answers nobody is
  waiting for.
* **Budgeted retries** — jittered exponential backoff for idempotent
  operations, capped by the remaining deadline (a retry that cannot
  finish in budget is not attempted).
* **Circuit breakers** — one closed/open/half-open breaker per remote
  target. Open breakers fast-fail instead of burning sockets and
  timeouts on a host that is down; a half-open probe re-closes the
  breaker when the target recovers. State is exported as gauges
  (``pio_breaker_state``) and transitions as counters.
* **Graceful drain** — SIGTERM flips ``GET /healthz`` from ``ok`` to
  ``draining``, new work is refused with 503 + ``Retry-After``,
  in-flight requests and the current device batch finish, then the
  server exits. Rolling restarts become lossless.
* **Fault injection** — a deterministic, seed-driven chaos middleware
  (env ``PIO_CHAOS``) that injects latency, errors, and connection
  resets at the HTTP boundary, so all of the above can be rehearsed
  (``scripts/chaos_smoke.py``) instead of first exercised by an outage.

Overload is NOT failure: the adaptive admission layer
(:mod:`~predictionio_tpu.serving.admission`) composes with these
primitives — a 429/503 shed carrying a computed ``Retry-After`` is the
server ANSWERING, so it never counts as a breaker failure, a
dependency's :class:`CircuitOpenError` fast-fail never feeds the
limiter's latency signal, and shed-retry hints are honored only inside
the propagated deadline budget (docs/robustness.md "Overload &
backpressure").

Env knobs (all optional; see docs/robustness.md):

* ``PIO_RETRY_MAX_ATTEMPTS`` (3), ``PIO_RETRY_BASE_MS`` (50),
  ``PIO_RETRY_MAX_MS`` (2000), ``PIO_RETRY_MULTIPLIER`` (2.0),
  ``PIO_RETRY_JITTER`` (0.5)
* ``PIO_BREAKER_FAILURES`` (5), ``PIO_BREAKER_RESET_S`` (30),
  ``PIO_BREAKER_HALF_OPEN_MAX`` (1)
* ``PIO_DRAIN_GRACE_S`` (30)
* ``PIO_CHAOS`` (e.g. ``latency:p=0.1,ms=200;error:p=0.05;reset:p=0.02``),
  ``PIO_CHAOS_SEED``
"""

from __future__ import annotations

import contextvars
import logging
import math
import os
import random
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable

from predictionio_tpu.obs import MetricRegistry, get_registry
from predictionio_tpu.obs import timeline as timeline_mod
from predictionio_tpu.obs.context import log_json

logger = logging.getLogger(__name__)

#: remaining time budget, in milliseconds, decremented across hops
DEADLINE_HEADER = "X-PIO-Deadline"


def _env_float(name: str, default: float) -> float:
    """One malformed-env policy for every knob in this module: warn
    and fall back to the default (a typo'd knob must degrade to stock
    resilience, never crash a server at startup)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        logger.warning("ignoring malformed %s", name)
        return default


class DeadlineExceeded(Exception):
    """The request's time budget ran out before the work happened."""


class CircuitOpenError(RuntimeError):
    """Fast-fail: the target's breaker is open (recent failures)."""

    def __init__(self, target: str, message: str | None = None):
        super().__init__(
            message
            or f"circuit open for {target}; fast-failing without a request"
        )
        self.target = target


# --------------------------------------------------------------------------
# deadlines
# --------------------------------------------------------------------------


class Deadline:
    """An absolute point on the monotonic clock a request must not
    outlive. Created from a *relative* budget (``after``/``from_header``)
    because wall clocks differ across hosts — only budgets travel on
    the wire, never absolute times."""

    __slots__ = ("expires_mono",)

    #: budgets above this are clamped (a hostile or buggy header must
    #: not pin a deadline years in the future)
    MAX_BUDGET_S = 3600.0

    def __init__(self, expires_mono: float):
        self.expires_mono = expires_mono

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + min(seconds, cls.MAX_BUDGET_S))

    @classmethod
    def from_header(cls, raw: str | None) -> "Deadline | None":
        """Parse an ``X-PIO-Deadline`` value (remaining ms). ``None``
        or malformed → no deadline; ``<= 0`` → an already-expired
        deadline (the admission check turns it into a 504)."""
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            ms = math.nan
        if not math.isfinite(ms):
            # nan/inf float()-parse fine but poison every later
            # comparison (nan bypasses the clamp AND `expired`) —
            # treat them as malformed
            logger.debug("ignoring malformed %s: %r", DEADLINE_HEADER, raw)
            return None
        return cls.after(max(ms, 0.0) / 1000.0)

    def remaining_s(self) -> float:
        return self.expires_mono - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def to_header(self) -> str:
        """The header value for the NEXT hop: whatever budget is left
        now (so the budget decrements across hops)."""
        return str(max(0, int(self.remaining_ms())))

    def cap(self, timeout_s: float) -> float:
        """``timeout_s`` bounded by the remaining budget (never below
        a tiny positive floor, so socket APIs don't treat it as
        blocking-forever)."""
        return max(0.001, min(timeout_s, self.remaining_s()))

    def reserved(self, seconds: float) -> "Deadline":
        """A deadline ending ``seconds`` earlier — the slice a caller
        holds back for one more hop (the router reserves failover
        budget this way). When the budget is already too tight to
        slice (less than twice the reservation), the full deadline is
        returned: starving the FIRST attempt to protect a retry that
        could never fit anyway helps nobody."""
        if self.remaining_s() <= seconds * 2.0:
            return self
        return Deadline(self.expires_mono - seconds)


_deadline: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "pio_deadline", default=None
)


def set_deadline(deadline: Deadline | None) -> None:
    """Install the request's deadline for the current context (the
    HTTP layer calls this once per request, ``None`` when the request
    carried no budget — which also clears any stale value left on a
    reused keep-alive handler thread)."""
    _deadline.set(deadline)


def get_deadline() -> Deadline | None:
    return _deadline.get()


# --------------------------------------------------------------------------
# retries
# --------------------------------------------------------------------------

#: HTTP methods safe to replay — the ONE definition the client SDK and
#: the store hop both use, so retry semantics cannot drift between them
#: (every store-DAO PUT here is a keyed upsert)
IDEMPOTENT_METHODS = ("GET", "HEAD", "PUT", "DELETE")

_RETRY_ENV_KEYS = (
    "PIO_RETRY_MAX_ATTEMPTS",
    "PIO_RETRY_BASE_MS",
    "PIO_RETRY_MULTIPLIER",
    "PIO_RETRY_MAX_MS",
    "PIO_RETRY_JITTER",
)
_retry_policy_cache: dict[tuple, "RetryPolicy"] = {}


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff for idempotent operations.

    ``max_attempts`` counts the first try: 3 means one request plus at
    most two retries. Jitter subtracts up to ``jitter`` of the raw
    delay (spreading retry storms instead of synchronizing them)."""

    max_attempts: int = 3
    base_backoff_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter: float = 0.5

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        # called per outbound request on hot paths: cache per env-value
        # tuple so a test's monkeypatched env still takes effect while
        # the steady state skips the parse + construction
        key = tuple(os.environ.get(k) for k in _RETRY_ENV_KEYS)
        cached = _retry_policy_cache.get(key)
        if cached is not None:
            return cached
        policy = cls(
            max_attempts=max(
                1, int(_env_float("PIO_RETRY_MAX_ATTEMPTS", 3))
            ),
            base_backoff_s=_env_float("PIO_RETRY_BASE_MS", 50.0) / 1000.0,
            multiplier=_env_float("PIO_RETRY_MULTIPLIER", 2.0),
            max_backoff_s=_env_float("PIO_RETRY_MAX_MS", 2000.0) / 1000.0,
            jitter=min(
                1.0, max(0.0, _env_float("PIO_RETRY_JITTER", 0.5))
            ),
        )
        _retry_policy_cache[key] = policy
        return policy

    def backoff_s(self, attempt: int, rng: random.Random | None = None) -> float:
        """Delay before retry number ``attempt`` (0-based: the delay
        after the first failure is ``backoff_s(0)``)."""
        raw = min(
            self.base_backoff_s * (self.multiplier ** attempt),
            self.max_backoff_s,
        )
        r = (rng or random).random()
        return raw * (1.0 - self.jitter * r)

    def sleep_before_retry(
        self,
        attempt: int,
        deadline: Deadline | None,
        rng: random.Random | None = None,
    ) -> bool:
        """Sleep for the backoff if another attempt fits the budget;
        returns False (without sleeping) when retries or budget are
        exhausted — the caller surfaces the last error."""
        if attempt + 1 >= self.max_attempts:
            return False
        delay = self.backoff_s(attempt, rng)
        if deadline is not None and deadline.remaining_s() <= delay:
            return False
        time.sleep(delay)
        return True


# --------------------------------------------------------------------------
# circuit breakers
# --------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: gauge encoding (documented in docs/robustness.md)
_STATE_VALUE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


@dataclass(frozen=True)
class BreakerConfig:
    failure_threshold: int = 5
    reset_after_s: float = 30.0
    half_open_max: int = 1

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        return cls(
            failure_threshold=max(
                1, int(_env_float("PIO_BREAKER_FAILURES", 5))
            ),
            reset_after_s=max(
                0.0, _env_float("PIO_BREAKER_RESET_S", 30.0)
            ),
            half_open_max=max(
                1, int(_env_float("PIO_BREAKER_HALF_OPEN_MAX", 1))
            ),
        )


class CircuitBreaker:
    """Per-target closed → open → half-open → closed state machine.

    * ``closed``: requests flow; ``failure_threshold`` CONSECUTIVE
      failures trip it open (any success resets the count).
    * ``open``: ``allow()`` returns False (callers fast-fail) until
      ``reset_after_s`` elapses, then the next ``allow()`` moves to
      half-open.
    * ``half_open``: up to ``half_open_max`` probe requests pass; a
      probe success re-closes the breaker, a probe failure re-trips it
      open (and restarts the reset clock).

    Callers MUST pair every allowed request with exactly one
    ``record_success``/``record_failure``. State is exported on
    ``registry`` as ``pio_breaker_state{target}`` (0=closed, 1=open,
    2=half-open) and transitions as
    ``pio_breaker_transitions_total{target,to}``.
    """

    def __init__(
        self,
        target: str,
        config: BreakerConfig | None = None,
        registry: MetricRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.target = target
        self.config = config or BreakerConfig.from_env()
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._half_open_inflight = 0
        #: idents of threads holding a probe slot in the CURRENT
        #: half-open episode — a verdict is only a probe verdict if the
        #: recording thread was admitted as a probe (callers are
        #: synchronous, so allow() and the matching record run on one
        #: thread); anything else in half-open is a stale pre-trip
        #: verdict that must not steal the probe's slot
        self._probe_threads: set[int] = set()
        registry = registry if registry is not None else get_registry()
        self._state_gauge = registry.gauge(
            "pio_breaker_state",
            "Circuit breaker state per target "
            "(0=closed, 1=open, 2=half-open)",
            ("target",),
        ).labels(target)
        self._transitions = registry.counter(
            "pio_breaker_transitions_total",
            "Circuit breaker transitions by target and destination state",
            ("target", "to"),
        )
        self._state_gauge.set(_STATE_VALUE[CLOSED])

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str) -> None:
        # lock held by caller
        self._state = to
        if to != HALF_OPEN:
            # probe bookkeeping is per half-open episode
            self._probe_threads.clear()
            self._half_open_inflight = 0
        self._state_gauge.set(_STATE_VALUE[to])
        self._transitions.labels(self.target, to).inc()
        # incident timeline: a breaker flip is exactly the kind of
        # control-plane event that explains a goodput dip. record() is
        # a deque append — safe under the breaker lock.
        timeline_mod.get_timeline().record(
            "breaker_transition",
            f"breaker {self.target!r} -> {to}",
            severity=(
                timeline_mod.ERROR
                if to == OPEN
                else timeline_mod.INFO
            ),
            target=self.target,
            to=to,
        )
        log_json(
            logger,
            logging.WARNING if to == OPEN else logging.INFO,
            "breaker_transition",
            target=self.target,
            to=to,
        )

    def allow(self) -> bool:
        """May a request go to the target right now? A True answer
        must be followed by record_success/record_failure."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if (
                    self._clock() - self._opened_at
                    < self.config.reset_after_s
                ):
                    return False
                self._transition(HALF_OPEN)
                self._half_open_inflight = 0
                self._probe_threads.clear()
            # half-open: admit a bounded number of probes
            if self._half_open_inflight >= self.config.half_open_max:
                return False
            self._half_open_inflight += 1
            self._probe_threads.add(threading.get_ident())
            return True

    def _release_probe_slot(self) -> bool:
        """Lock held. True when the CALLING thread holds a probe slot
        in the current half-open episode (and releases it); a verdict
        from any other request predates the trip and proves nothing."""
        ident = threading.get_ident()
        if ident not in self._probe_threads:
            return False
        self._probe_threads.discard(ident)
        self._half_open_inflight = max(0, self._half_open_inflight - 1)
        return True

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                if not self._release_probe_slot():
                    return  # stale pre-trip verdict: ignore
                self._failures = 0
                self._transition(CLOSED)
            elif self._state == CLOSED:
                self._failures = 0
            # open: a late success from a request admitted before the
            # trip proves nothing about recovery — the reset clock rules

    def release(self) -> None:
        """The admitted request produced NO evidence about the target —
        it was never delivered whole (stale keep-alive replay) or the
        caller's own budget expired before the target could answer.
        Releases a half-open probe slot without a verdict; without this
        a verdict-less probe would wedge the breaker half-open forever."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._release_probe_slot()

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                if not self._release_probe_slot():
                    # a LATE failure from a request admitted before the
                    # trip: like a late success in OPEN, it predates
                    # this episode — re-tripping (or stealing the
                    # outstanding probe's slot) would delay a recovered
                    # target by another reset window
                    return
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.config.failure_threshold:
                    self._opened_at = self._clock()
                    self._transition(OPEN)
            # open: already tripped; more failures don't restart the clock
            # (a recovering target must get its half-open probe on time)


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(
    target: str,
    config: BreakerConfig | None = None,
    registry: MetricRegistry | None = None,
) -> CircuitBreaker:
    """The process-wide breaker for ``target`` (``host:port``); created
    on first use (``config``/``registry`` only apply then — every later
    caller shares the same state, which is the point)."""
    with _breakers_lock:
        breaker = _breakers.get(target)
        if breaker is None:
            breaker = CircuitBreaker(
                target, config=config, registry=registry
            )
            _breakers[target] = breaker
        return breaker


def reset_breakers() -> None:
    """Forget all breaker state (tests)."""
    with _breakers_lock:
        _breakers.clear()


# --------------------------------------------------------------------------
# graceful drain
# --------------------------------------------------------------------------


class DrainState:
    """Shared between the HTTP handler threads (begin/end per request)
    and the drain sequence (waits for in-flight to reach zero)."""

    __slots__ = ("draining", "_lock", "_inflight")

    def __init__(self):
        self.draining = threading.Event()
        self._lock = threading.Lock()
        self._inflight = 0

    def begin_request(self) -> None:
        with self._lock:
            self._inflight += 1

    def end_request(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight


def drain_grace_s() -> float:
    return _env_float("PIO_DRAIN_GRACE_S", 30.0)


def install_signal_drain(
    *servers, grace_s: float | None = None
) -> Callable[[], None]:
    """SIGTERM → graceful drain for ``servers`` (HTTPServer instances).

    The handler immediately flips every server's ``/healthz`` to
    ``draining`` (load balancers stop routing), then a background
    thread runs each server's full drain: refuse new work with 503,
    wait for in-flight requests (bounded by ``grace_s`` /
    ``PIO_DRAIN_GRACE_S``), run drain hooks (closing micro-batchers —
    the current device batch finishes), and shut the listener down,
    which returns ``serve_forever`` and lets the process exit.

    Returns a callable restoring the previous handler (tests)."""

    def _handler(signum, frame):
        log_json(
            logger, logging.WARNING, "sigterm_drain",
            servers=len(servers),
        )
        for server in servers:
            server.begin_drain()

        def _go():
            for server in servers:
                server.drain(grace_s=grace_s)

        threading.Thread(target=_go, name="pio-drain", daemon=True).start()

    try:
        previous = signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        # not the main thread (embedded/test usage): drain must be
        # driven explicitly via server.drain()
        return lambda: None

    def _restore() -> None:
        signal.signal(signal.SIGTERM, previous)

    return _restore


# --------------------------------------------------------------------------
# fault injection
# --------------------------------------------------------------------------


class ChaosError(Exception):
    """Injected HTTP error (the middleware's ``error`` fault)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class ChaosReset(Exception):
    """Injected connection reset: the HTTP layer slams the socket shut
    without writing a response (the client sees a peer reset — the
    exact failure a crashed server produces)."""


class ChaosPartition(ChaosReset):
    """Injected network partition: the connection is accepted and the
    request read, then the handler holds the socket for ``ms`` (packets
    into a black hole — the client just waits) before slamming it shut
    without a response. Subclasses :class:`ChaosReset` so the HTTP
    layer's no-response socket-close path handles both."""


@dataclass(frozen=True)
class _ChaosRule:
    fault: str  # latency | error | reset | partition
    p: float
    ms: float = 0.0
    status: int = 503


class ChaosMiddleware:
    """Deterministic, seed-driven fault injector for the HTTP boundary.

    Spec format (env ``PIO_CHAOS``), semicolon-separated rules::

        latency:p=0.1,ms=200;error:p=0.05;reset:p=0.02;partition:p=0.01,ms=100

    Rules are evaluated in order per request, each consuming exactly
    one PRNG draw — so for a given seed (``PIO_CHAOS_SEED``) and a
    serialized request sequence the fault schedule is reproducible.
    ``latency`` sleeps and continues to the next rule; ``error`` raises
    :class:`ChaosError` (default status 503, override with
    ``status=``); ``reset`` raises :class:`ChaosReset`; ``partition``
    accepts the connection, holds it for ``ms`` (default 0), then
    raises :class:`ChaosPartition` — the client sees a stall followed
    by a dead socket with no response, the shape of a network
    partition rather than a crashed process.

    The telemetry surface (``/healthz``, ``/metrics*``, ``/debug/*``)
    is exempted by the HTTP layer: chaos must not blind the operator
    watching the experiment. Injections are counted in
    ``pio_chaos_injected_total{fault}``. Flip :attr:`enabled` to stage
    brownouts mid-run (``scripts/chaos_smoke.py`` does)."""

    def __init__(
        self,
        rules: list[_ChaosRule] | str,
        seed: int | None = None,
        registry: MetricRegistry | None = None,
    ):
        self.rules = self.parse(rules) if isinstance(rules, str) else rules
        self.enabled = True
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        registry = registry if registry is not None else get_registry()
        self._injected = registry.counter(
            "pio_chaos_injected_total",
            "Faults injected by the chaos middleware, by fault kind",
            ("fault",),
        )

    @staticmethod
    def parse(spec: str) -> list[_ChaosRule]:
        rules: list[_ChaosRule] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            fault, _, arg_str = part.partition(":")
            fault = fault.strip()
            if fault not in ("latency", "error", "reset", "partition"):
                raise ValueError(
                    f"chaos spec: unknown fault {fault!r} "
                    "(expected latency|error|reset|partition)"
                )
            args: dict[str, float] = {}
            for pair in filter(None, arg_str.split(",")):
                key, _, value = pair.partition("=")
                try:
                    args[key.strip()] = float(value)
                except ValueError as e:
                    raise ValueError(
                        f"chaos spec: bad value in {pair!r}"
                    ) from e
            p = args.pop("p", None)
            if p is None or not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"chaos spec: {fault} needs p=<0..1>, got {p!r}"
                )
            ms = args.pop("ms", 0.0)
            status = int(args.pop("status", 503))
            if args:
                raise ValueError(
                    f"chaos spec: unknown args for {fault}: "
                    f"{sorted(args)}"
                )
            rules.append(_ChaosRule(fault=fault, p=p, ms=ms, status=status))
        if not rules:
            raise ValueError("chaos spec parsed to no rules")
        return rules

    @classmethod
    def from_env(
        cls, registry: MetricRegistry | None = None
    ) -> "ChaosMiddleware | None":
        spec = os.environ.get("PIO_CHAOS")
        if not spec:
            return None
        seed_raw = os.environ.get("PIO_CHAOS_SEED")
        seed = int(seed_raw) if seed_raw else None
        middleware = cls(spec, seed=seed, registry=registry)
        log_json(
            logger, logging.WARNING, "chaos_enabled",
            spec=spec, seed=seed,
        )
        return middleware

    def apply(self, path: str) -> None:
        """Run the rule chain for one request; sleeps and/or raises."""
        if not self.enabled:
            return
        for rule in self.rules:
            with self._lock:
                hit = self._rng.random() < rule.p
            if not hit:
                continue
            self._injected.labels(rule.fault).inc()
            if rule.fault == "latency":
                time.sleep(rule.ms / 1000.0)
            elif rule.fault == "error":
                raise ChaosError(
                    rule.status, f"chaos: injected error on {path}"
                )
            elif rule.fault == "partition":
                # accept, swallow, stall, then reset without a
                # response — what a mid-connection network partition
                # looks like from the client side (vs `reset`, which
                # fails fast like a crashed process)
                if rule.ms > 0:
                    time.sleep(rule.ms / 1000.0)
                raise ChaosPartition()
            else:  # reset
                raise ChaosReset()
