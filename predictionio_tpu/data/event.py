"""Event model + validation.

Capability parity with the reference ``data/.../storage/Event.scala:39-164``:
an immutable behavioral event with entity / optional target-entity
coordinates, a property bag, event time, tags, and an optional ``prId``
linking a ``predict`` feedback event to the prediction that caused it.

Validation rules mirror ``EventValidation`` (Event.scala:109-164):

* names starting with ``$`` are reserved; only the special events
  ``$set / $unset / $delete`` are accepted;
* ``pio_``-prefixed event names, entity types, target entity types and
  property keys are reserved (except built-ins, e.g. entity type
  ``pio_pr`` used by the prediction-feedback loop);
* special events must not carry a target entity; ``$unset`` must carry a
  non-empty property bag.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import uuid
from typing import Any, Mapping

from predictionio_tpu.data.datamap import DataMap

SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
#: Built-in entity types exempt from the ``pio_`` reservation
#: (reference Event.scala:158-164 — ``pio_pr`` backs the feedback loop).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
DEFAULT_ENTITY_ID = ""


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class EventValidationError(ValueError):
    """Raised for events violating the reserved-name / shape rules."""


@dataclasses.dataclass(frozen=True)
class Event:
    """One behavioral event (reference Event.scala:39-75)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: str | None = None
    target_entity_id: str | None = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=_utcnow)
    tags: tuple[str, ...] = ()
    pr_id: str | None = None
    event_id: str | None = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=_utcnow)

    def __post_init__(self) -> None:
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        for name in ("event_time", "creation_time"):
            t = getattr(self, name)
            if t.tzinfo is None:  # naive timestamps are taken as UTC
                object.__setattr__(
                    self, name, t.replace(tzinfo=_dt.timezone.utc)
                )
        validate_event(self)

    def with_id(self, event_id: str | None = None) -> "Event":
        """Return a copy carrying a concrete event id (UUID4 by default)."""
        return dataclasses.replace(
            self, event_id=event_id or uuid.uuid4().hex
        )

    # -- JSON (API shape; reference EventJson4sSupport.APISerializer) -----
    def to_json_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.to_dict(),
            "eventTime": self.event_time.isoformat(),
            "creationTime": self.creation_time.isoformat(),
        }
        if self.event_id is not None:
            d["eventId"] = self.event_id
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        return d

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Event":
        """Parse the API JSON shape (reference EventJson4sSupport.scala:35-118)."""
        try:
            event = d["event"]
            entity_type = d["entityType"]
            entity_id = d["entityId"]
        except KeyError as e:
            raise EventValidationError(f"field {e.args[0]} is required") from e

        def _time(key: str) -> _dt.datetime:
            raw = d.get(key)
            if raw is None or raw == "":
                return _utcnow()
            try:
                t = _dt.datetime.fromisoformat(
                    str(raw).replace("Z", "+00:00")
                )
            except ValueError as e:
                raise EventValidationError(
                    f"{key} {raw!r} is not an ISO-8601 time: {e}"
                ) from e
            return t if t.tzinfo else t.replace(tzinfo=_dt.timezone.utc)

        return Event(
            event=str(event),
            entity_type=str(entity_type),
            entity_id=str(entity_id),
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=d.get("targetEntityId"),
            properties=DataMap(d.get("properties") or {}),
            event_time=_time("eventTime"),
            tags=tuple(d.get("tags") or ()),
            pr_id=d.get("prId"),
            event_id=d.get("eventId"),
            creation_time=_time("creationTime"),
        )


def validate_event(e: Event) -> None:
    """Enforce the reference's event rules (Event.scala:109-164)."""
    if not e.event:
        raise EventValidationError("event must not be empty.")
    if not e.entity_type:
        raise EventValidationError("entityType must not be empty string.")
    if not e.entity_id:
        raise EventValidationError("entityId must not be empty string.")
    if e.target_entity_type is not None and not e.target_entity_type:
        raise EventValidationError(
            "targetEntityType must not be empty string."
        )
    if e.target_entity_id is not None and not e.target_entity_id:
        raise EventValidationError("targetEntityId must not be empty string.")
    if (e.target_entity_type is None) != (e.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together."
        )

    # Reserved prefixes (Event.scala:120-141)
    if e.event.startswith("$") and e.event not in SPECIAL_EVENTS:
        raise EventValidationError(
            f"{e.event} is not a supported reserved event name."
        )
    if e.event.startswith("pio_"):
        raise EventValidationError(
            f"{e.event} is not a supported reserved event name."
        )
    for who, etype in (
        ("entityType", e.entity_type),
        ("targetEntityType", e.target_entity_type),
    ):
        if (
            etype is not None
            and etype.startswith("pio_")
            and etype not in BUILTIN_ENTITY_TYPES
        ):
            raise EventValidationError(
                f"{etype} is not a supported reserved {who}."
            )
    for key in e.properties:
        if key.startswith("pio_"):
            raise EventValidationError(
                f"{key} is not a supported reserved property key."
            )

    # Special-event shape rules (Event.scala:143-156)
    if e.event in SPECIAL_EVENTS:
        if e.target_entity_type is not None or e.target_entity_id is not None:
            raise EventValidationError(
                f"special event {e.event} must not have targetEntity."
            )
        if e.event == "$unset" and len(e.properties) == 0:
            raise EventValidationError(
                "$unset event must have non-empty properties."
            )
