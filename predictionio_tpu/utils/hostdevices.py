"""XLA host-platform virtual-device pinning (pre-jax-import).

The CPU backend honours ``--xla_force_host_platform_device_count`` only
at client creation, so the flag must land in ``XLA_FLAGS`` BEFORE jax
initializes — later edits no-op silently. Every multi-device harness in
the repo (tests/conftest.py, ``__graft_entry__.dryrun_multichip``, the
``tests/distributed*_child.py`` processes, ``scripts/multichip_bench.py``
workers) shares THIS helper so the set-or-rewrite contract lives in one
place. This module must stay importable without jax.
"""

from __future__ import annotations

import os
import re

_OPT = "--xla_force_host_platform_device_count"


def force_host_platform_device_count(n: int, *, exact: bool = False) -> None:
    """Pin the CPU host platform to ``n`` virtual devices via
    ``XLA_FLAGS``, preserving every other flag.

    An existing pin is raised to ``n`` when lower and otherwise left
    alone (``exact=False`` — the test-harness/dryrun contract: never
    shrink a wider pin another harness set), or rewritten to exactly
    ``n`` (``exact=True`` — the multichip bench's per-worker sweep,
    where each device count must be measured at precisely that count).
    """
    if n < 1:
        raise ValueError(f"device count must be >= 1, got {n}")
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_OPT}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_OPT}={n}".strip()
    elif (exact and int(m.group(1)) != n) or (
        not exact and int(m.group(1)) < n
    ):
        os.environ["XLA_FLAGS"] = re.sub(
            rf"{_OPT}=\d+", f"{_OPT}={n}", flags
        )
