"""SO_REUSEPORT multi-worker front-end (serving/workers.py).

Reference analogue: the spray HTTP tier scales across cores with JVM
threads (CreateServer.scala:495-647); the Python front-end scales with
worker processes sharing one port. These tests prove the mechanics on
a live port: N processes bound together, kernel load-balancing across
them, crashed workers respawned, clean group teardown.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

import threading

from predictionio_tpu.serving.workers import (
    _HEALTHY_UPTIME_S,
    _RESPAWN_DELAY_S,
    _RESPAWN_MAX_DELAY_S,
    WorkerSlot,
    backoff_delay_s,
    rebuild_argv,
    supervise_children,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestRebuildArgv:
    def test_pins_port_and_resets_workers(self):
        argv = ["eventserver", "--ip", "127.0.0.1", "--port", "0",
                "--workers", "4", "--stats"]
        out = rebuild_argv(argv, 7070)
        assert out == [
            "eventserver", "--ip", "127.0.0.1", "--stats",
            "--port", "7070", "--workers", "1", "--reuse-port",
        ]

    def test_equals_style_options(self):
        out = rebuild_argv(
            ["eventserver", "--port=0", "--workers=3"], 8123
        )
        assert out == [
            "eventserver", "--port", "8123", "--workers", "1",
            "--reuse-port",
        ]

    def test_port_equals_form_only(self):
        """`--port=N` alone (no --workers) is rewritten, not kept as a
        stale duplicate ahead of the pinned port."""
        out = rebuild_argv(["deploy", "--port=8000"], 8001)
        assert out == [
            "deploy", "--port", "8001", "--workers", "1", "--reuse-port",
        ]
        assert "--port=8000" not in out

    def test_repeated_workers_flags_all_stripped(self):
        out = rebuild_argv(
            ["eventserver", "--workers", "4", "--workers=8",
             "--workers", "2"],
            7070,
        )
        assert out == [
            "eventserver", "--port", "7070", "--workers", "1",
            "--reuse-port",
        ]

    def test_value_that_looks_like_flag_is_consumed(self):
        """`--workers 4 --port 0`: each option consumes ITS value even
        when values and option names interleave."""
        out = rebuild_argv(
            ["deploy", "--workers", "4", "--port", "0", "--variant",
             "e.json"],
            9000,
        )
        assert out == [
            "deploy", "--variant", "e.json",
            "--port", "9000", "--workers", "1", "--reuse-port",
        ]

    def test_existing_reuse_port_not_duplicated(self):
        out = rebuild_argv(["eventserver", "--reuse-port"], 9)
        assert out.count("--reuse-port") == 1


class _FakeProc:
    """Popen stand-in: scripted exit at a clock time."""

    _next_pid = 1000

    def __init__(self, clock, dies_at=None, rc=1):
        _FakeProc._next_pid += 1
        self.pid = _FakeProc._next_pid
        self._clock = clock
        self.dies_at = dies_at
        self.rc = rc
        self.terminated = False

    def poll(self):
        if self.dies_at is not None and self._clock() >= self.dies_at:
            return self.rc
        return None

    def terminate(self):
        self.terminated = True
        self.dies_at = self._clock()
        self.rc = -15


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _run_supervisor_step(slots, clock, steps=1):
    """Run supervise_children for `steps` poll iterations at the fake
    clock's current time, then stop it."""
    stopping = threading.Event()
    count = {"n": 0}
    real_wait = stopping.wait

    def counting_wait(timeout=None):
        count["n"] += 1
        if count["n"] >= steps:
            stopping.set()
        return real_wait(0)

    stopping.wait = counting_wait
    supervise_children(
        slots, stopping, clock=clock, poll_interval_s=0.0
    )


class TestRespawnBackoff:
    def test_backoff_delay_escalates_and_caps(self):
        delays = [backoff_delay_s(f) for f in range(0, 8)]
        assert delays[0] == delays[1] == _RESPAWN_DELAY_S
        assert delays[2] == 2 * _RESPAWN_DELAY_S
        assert delays[3] == 4 * _RESPAWN_DELAY_S
        assert delays[-1] == _RESPAWN_MAX_DELAY_S

    def test_crash_loop_escalates_backoff(self):
        """A child that binds then dies young keeps DOUBLING the delay;
        a long-lived child resets it."""
        clock = _Clock()
        spawned = []

        def spawn():
            # each respawn dies 1s after it starts (young: < healthy)
            proc = _FakeProc(clock, dies_at=clock.t + 1.0)
            spawned.append(proc)
            return proc

        slot = WorkerSlot(spawn, clock=clock)
        delays = []
        for _ in range(5):
            # advance to the child's death and let the supervisor see it
            clock.t = slot.spawned_at + 1.0
            _run_supervisor_step([slot], clock)
            assert slot.proc is None
            delays.append(slot.respawn_at - clock.t)
            # advance past the respawn deadline so it respawns
            clock.t = slot.respawn_at
            _run_supervisor_step([slot], clock)
            assert slot.proc is not None
        assert delays == [1.0, 2.0, 4.0, 8.0, 16.0]
        # now the child serves past the healthy-uptime bar: clock resets
        slot.proc.dies_at = clock.t + _HEALTHY_UPTIME_S + 1.0
        clock.t = slot.proc.dies_at
        _run_supervisor_step([slot], clock)
        assert slot.fails == 0
        assert slot.respawn_at - clock.t == _RESPAWN_DELAY_S

    def test_sibling_backoff_does_not_reset_fast_cracher(self):
        """THE bug the old inline-sleep supervisor had: while slot A
        waits out a 30s backoff, slot B's child binds, serves 2s, and
        dies — B's uptime must read ~2s (escalating ITS backoff), not
        2s + A's sleep (which reset it and turned B's crash loop into
        a hot spin)."""
        clock = _Clock()

        def spawn_b():
            return _FakeProc(clock, dies_at=clock.t + 2.0)

        slot_a = WorkerSlot(lambda: _FakeProc(clock), clock=clock)
        slot_b = WorkerSlot(spawn_b, clock=clock)
        # A is already deep in backoff: respawn 30s out
        slot_a.proc = None
        slot_a.fails = 6
        slot_a.respawn_at = clock.t + 30.0
        # B dies young, repeatedly, while A waits
        delays = []
        for _ in range(3):
            clock.t = slot_b.spawned_at + 2.0
            _run_supervisor_step([slot_a, slot_b], clock)
            assert slot_b.proc is None, "B's exit went unnoticed"
            delays.append(slot_b.respawn_at - clock.t)
            clock.t = slot_b.respawn_at
            _run_supervisor_step([slot_a, slot_b], clock)
        # escalating, never reset by A's pending backoff
        assert delays == [1.0, 2.0, 4.0]
        assert slot_b.fails == 3

    def test_no_respawn_after_stopping(self):
        clock = _Clock()
        spawned = []

        def spawn():
            proc = _FakeProc(clock, dies_at=clock.t + 1.0)
            spawned.append(proc)
            return proc

        slot = WorkerSlot(spawn, clock=clock)
        clock.t = 2.0
        stopping = threading.Event()
        stopping.set()
        supervise_children(
            [slot], stopping, clock=clock, poll_interval_s=0.0
        )
        assert spawned == [slot.proc]  # nothing new spawned

    def test_adopts_existing_process(self):
        clock = _Clock()
        existing = _FakeProc(clock)
        slot = WorkerSlot(
            lambda: _FakeProc(clock), clock=clock, proc=existing
        )
        assert slot.proc is existing


class TestDynamicSlots:
    """The autoscaler grows/shrinks the slot list while the loop runs:
    appended slots are picked up, retired slots drop out with their
    pending respawns cancelled, and no slot's backoff deadline leaks
    into a sibling's."""

    def test_retire_mid_backoff_cancels_pending_respawn(self):
        clock = _Clock()
        spawned = []

        def spawn():
            proc = _FakeProc(clock, dies_at=clock.t + 1.0)
            spawned.append(proc)
            return proc

        slot = WorkerSlot(spawn, clock=clock)
        slots = [slot]
        clock.t = slot.spawned_at + 1.0
        _run_supervisor_step(slots, clock)
        assert slot.proc is None and slot.respawn_at > clock.t
        slot.retire()
        clock.t = slot.respawn_at + 5.0
        _run_supervisor_step(slots, clock)
        assert slots == []               # dropped from supervision
        assert slot.proc is None         # and NEVER respawned
        assert len(spawned) == 1

    def test_retired_slot_with_live_proc_is_released_not_killed(self):
        """Retiring a slot whose child is alive (the drain path owns
        that process now) only releases supervision: the process object
        is untouched and a later exit is not respawned."""
        clock = _Clock()
        spawned = []

        def spawn():
            proc = _FakeProc(clock, dies_at=clock.t + 100.0)
            spawned.append(proc)
            return proc

        slot = WorkerSlot(spawn, clock=clock)
        live = slot.proc
        slots = [slot]
        slot.retire()
        _run_supervisor_step(slots, clock)
        assert slots == [] and slot.proc is live
        assert not live.terminated  # the drain path owns this process
        # the process dies later (SIGTERM drain finished): no respawn
        clock.t = 200.0
        _run_supervisor_step(slots, clock)
        assert spawned == [live]

    def test_respawn_racing_retirement_is_terminated_at_removal(self):
        """retire() lands while the supervisor is respawning the slot
        (mid-backoff, deadline due): the freshly spawned process was
        never seen by the retirer — nothing will ever drain it — so the
        supervisor must terminate it when it drops the slot, instead of
        leaking a live orphan."""
        clock = _Clock()
        slot = WorkerSlot(
            lambda: _FakeProc(clock, dies_at=clock.t + 100.0),
            clock=clock,
        )
        slot.proc = None           # mid-backoff: no live process
        slot.respawn_at = 5.0
        slots = [slot]
        slot.retire()              # retirer saw NO process to drain
        assert slot.retired_pid is None
        # the race: a respawn that was already past the retired-check
        # assigns a new process after the flag was set
        raced = _FakeProc(clock, dies_at=clock.t + 100.0)
        slot.proc = raced
        _run_supervisor_step(slots, clock)
        assert slots == []
        assert raced.terminated    # leak closed, orphan reaped

    def test_appended_slot_supervised_next_poll(self):
        clock = _Clock()
        slot_a = WorkerSlot(
            lambda: _FakeProc(clock, dies_at=clock.t + 100.0),
            clock=clock,
        )
        slots = [slot_a]
        _run_supervisor_step(slots, clock)
        # the autoscaler appends a new slot mid-run; its child dies
        slot_b = WorkerSlot(
            lambda: _FakeProc(clock, dies_at=clock.t + 1.0),
            clock=clock,
        )
        slots.append(slot_b)
        clock.t = slot_b.spawned_at + 1.0
        _run_supervisor_step(slots, clock)
        assert slot_b.proc is None          # exit noticed
        assert slot_b.respawn_at > clock.t  # backoff scheduled
        assert slot_a.proc is not None      # sibling untouched

    def test_retire_does_not_disturb_sibling_backoff(self):
        """No respawn-deadline cross-talk: slot A retiring mid-backoff
        neither advances nor delays slot B's own respawn deadline."""
        clock = _Clock()
        slot_a = WorkerSlot(lambda: _FakeProc(clock), clock=clock)
        slot_b = WorkerSlot(lambda: _FakeProc(clock), clock=clock)
        slot_a.proc = None
        slot_a.fails = 3
        slot_a.respawn_at = 4.0
        slot_b.proc = None
        slot_b.fails = 1
        slot_b.respawn_at = 10.0
        slots = [slot_a, slot_b]
        slot_a.retire()
        clock.t = 5.0  # past A's deadline, before B's
        _run_supervisor_step(slots, clock)
        assert slots == [slot_b]
        assert slot_a.proc is None          # A's respawn cancelled
        assert slot_b.proc is None          # B still waiting ITS deadline
        assert slot_b.respawn_at == 10.0
        clock.t = 10.0
        _run_supervisor_step(slots, clock)
        assert slot_b.proc is not None      # B respawned on schedule

    def test_concurrent_retire_of_same_slot_is_safe(self):
        """Two removals of one slot (reconcile + prune racing) must not
        crash the loop."""
        clock = _Clock()
        slot = WorkerSlot(lambda: _FakeProc(clock), clock=clock)
        slot.retire()
        slots = [slot, slot]  # worst case: listed twice
        _run_supervisor_step(slots, clock)
        assert slots == []


def _get_status(port: int) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10
    ) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def worker_group(tmp_path):
    """A 3-worker event server via the real CLI; yields (proc, port)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        # the banner must cross the pipe before serve_forever()
        "PYTHONUNBUFFERED": "1",
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "ev.sqlite"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "predictionio_tpu.cli.main",
            "eventserver", "--ip", "127.0.0.1", "--port", "0",
            "--workers", "3",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    port = _read_banner_port(proc)
    assert port, "server never reported its port"
    _drain(proc)
    # wait until requests are answered
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            _get_status(port)
            break
        except OSError:
            time.sleep(0.2)
    try:
        yield proc, port, str(tmp_path / "ev.sqlite")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def _read_banner_port(proc, timeout: float = 60.0) -> int | None:
    """Bounded read of the 'listening on host:port' banner — a server
    that wedges before printing must fail the test, not hang it."""
    import threading

    result: list[int] = []

    def _scan():
        for line in proc.stdout:
            if "listening on" in line:
                result.append(int(line.rsplit(":", 1)[1]))
                return

    t = threading.Thread(target=_scan, daemon=True)
    t.start()
    t.join(timeout)
    return result[0] if result else None


def _drain(proc) -> None:
    """Keep the merged stdout/stderr pipe drained: with request logging
    on, a full 64 KB pipe buffer would block the server mid-test."""
    import threading

    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()


def _worker_pids(parent_pid: int) -> set[int]:
    """Child pids of the parent that are re-exec'd workers."""
    out = subprocess.run(
        ["pgrep", "-P", str(parent_pid)],
        capture_output=True, text=True,
    )
    return {int(p) for p in out.stdout.split()}


class TestMultiWorkerEventServer:
    def test_kernel_balances_across_processes(self, worker_group):
        proc, port, _db = worker_group
        # each request opens a fresh connection; SO_REUSEPORT assigns
        # connections across the bound processes. Children take a
        # couple of seconds to import + bind, so poll until at least 2
        # distinct pids have answered.
        pids: set[int] = set()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(pids) < 2:
            pids.add(_get_status(port)["pid"])
        assert len(pids) >= 2, f"only one worker ever answered: {pids}"
        # and the answering pids really are the parent + its children
        group = {proc.pid} | _worker_pids(proc.pid)
        assert pids <= group

    def test_events_visible_across_workers(self, worker_group):
        """A write accepted by one worker is readable through any other
        (shared sqlite backend) — the property the memory backend
        cannot give a worker group."""
        _proc, port, db_path = worker_group
        # the event API needs an access key — create one against the
        # same sqlite file the workers share
        from predictionio_tpu.data.storage import AccessKey, App, Storage

        env_file = db_path
        storage = Storage(
            env={
                "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQL_PATH": env_file,
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
            }
        )
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="wapp")
        )
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="wkey", appid=app_id)
        )
        storage.get_events().init(app_id)
        body = json.dumps({
            "event": "buy",
            "entityType": "user",
            "entityId": "u1",
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/events.json?accessKey=wkey",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 201
        # read back until at least two distinct workers have served the
        # find (children need a moment to import + bind)
        seen_pids: set[int] = set()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(seen_pids) < 2:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/events.json?accessKey=wkey",
                timeout=10,
            ) as resp:
                events = json.loads(resp.read())
            assert len(events) == 1 and events[0]["event"] == "buy"
            seen_pids.add(_get_status(port)["pid"])
        assert len(seen_pids) >= 2

    def test_crashed_worker_respawns(self, worker_group):
        proc, port, _db = worker_group
        before = _worker_pids(proc.pid)
        assert len(before) == 2
        victim = min(before)
        os.kill(victim, signal.SIGKILL)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            now = _worker_pids(proc.pid)
            if len(now) == 2 and victim not in now:
                break
            time.sleep(0.3)
        else:
            pytest.fail("killed worker was not respawned")
        # the group still serves
        assert _get_status(port)["status"] == "alive"

    def test_multi_worker_deploy_serves_from_all_workers(self, tmp_path):
        """`deploy --workers 2`: every worker stages the model from the
        shared sqlite store and they all answer queries identically —
        the CPU-front topology docs/serving.md describes."""
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.update({
            "PYTHONUNBUFFERED": "1",
            "JAX_PLATFORMS": "cpu",
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "d.sqlite"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
        })

        def pio(*argv, timeout=300):
            return subprocess.run(
                [sys.executable, "-m", "predictionio_tpu.cli.main",
                 *argv],
                env=env, capture_output=True, text=True, timeout=timeout,
            )

        # seed + train the lead-scoring example (fast, deterministic)
        out = pio("app", "new", "MyLeadApp")
        assert out.returncode == 0, out.stderr
        import re as _re

        key = _re.search(r"Access Key:\s*(\S+)", out.stdout).group(1)
        examples = os.path.join(_REPO, "examples", "leadscoring")
        es = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli.main",
             "eventserver", "--ip", "127.0.0.1", "--port", "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            port = _read_banner_port(es)
            assert port
            _drain(es)
            seed = subprocess.run(
                [sys.executable,
                 os.path.join(examples, "import_eventserver.py"),
                 f"--access-key={key}",
                 "--url", f"http://127.0.0.1:{port}",
                 "--leads", "40"],
                env=env, capture_output=True, text=True, timeout=240,
            )
            assert seed.returncode == 0, seed.stderr
        finally:
            es.terminate()
            try:
                es.wait(timeout=10)
            except subprocess.TimeoutExpired:
                es.kill()
        variant = os.path.join(examples, "engine.json")
        out = pio("train", "--variant", variant, timeout=600)
        assert out.returncode == 0, out.stderr

        srv = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli.main",
             "deploy", "--variant", variant,
             "--ip", "127.0.0.1", "--port", "0", "--workers", "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            port = _read_banner_port(srv, timeout=180)
            assert port
            _drain(srv)

            import http.client

            # a keep-alive connection stays pinned to whichever worker
            # the kernel assigned it — collect one connection PER
            # worker, then send a query down each, so both workers
            # provably answer queries (status-only pids would not show
            # where the queries landed)
            body = json.dumps({"features": [8.0, 24.0, 40.0]})
            by_pid: dict[int, http.client.HTTPConnection] = {}
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and len(by_pid) < 2:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=30
                )
                try:
                    conn.request("GET", "/")
                    resp = conn.getresponse()
                    pid = json.loads(resp.read())["pid"]
                except OSError:
                    conn.close()
                    time.sleep(0.5)
                    continue
                if pid in by_pid:
                    conn.close()
                    time.sleep(0.2)
                else:
                    by_pid[pid] = conn
            assert len(by_pid) == 2, f"only {set(by_pid)} answered"
            answers = []
            try:
                for pid, conn in by_pid.items():
                    conn.request(
                        "POST", "/queries.json", body,
                        {"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    assert resp.status == 200, (pid, resp.status)
                    answers.append(json.loads(resp.read()))
            finally:
                for conn in by_pid.values():
                    conn.close()
            assert all(a["converted"] is True for a in answers)
            scores = {round(a["score"], 5) for a in answers}
            assert len(scores) == 1, f"workers disagree: {scores}"
        finally:
            if srv.poll() is None:
                srv.send_signal(signal.SIGTERM)
                try:
                    srv.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    srv.kill()

    def test_sigterm_tears_down_group(self, worker_group):
        proc, port, _db = worker_group
        children = _worker_pids(proc.pid)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            alive = {
                pid for pid in children
                if os.path.isdir(f"/proc/{pid}")
                and "zombie" not in open(f"/proc/{pid}/status").read()
            }
            if not alive:
                break
            time.sleep(0.2)
        assert not alive, f"workers survived parent: {alive}"
