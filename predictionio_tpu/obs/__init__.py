"""Unified telemetry: metric registry + request-ID propagation.

The reference's only observability is the event-server StatsActor
counters and the Spark UI (SURVEY §5); a server meant to sustain heavy
multi-user traffic needs to see where latency goes. This package is the
one system both sides feed: serving records per-route latency, batch
occupancy, and device-dispatch time into it; training loops publish
:class:`~predictionio_tpu.utils.profiling.StepTimer` records into it;
every server scrapes it at ``GET /metrics`` (Prometheus text) and
``GET /metrics.json``.

Stdlib-only by design — the serving layer imports it, never the other
way around, so there is no import cycle and no hot-path dependency
beyond a dict lookup and a lock.
"""

from predictionio_tpu.obs.context import (
    get_request_id,
    new_request_id,
    set_request_id,
)
from predictionio_tpu.obs.device import CompileTracker, DeviceSampler
from predictionio_tpu.obs.federation import (
    combine_families,
    counter_total,
    merge_payloads,
    render_prometheus_families,
)
from predictionio_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    MetricRegistry,
    TRAIN_STEP_BUCKETS,
    get_registry,
)
from predictionio_tpu.obs.slo import Objective, SLOMonitor
from predictionio_tpu.obs.timeline import (
    Timeline,
    get_timeline,
    merge_timelines,
    set_timeline,
)
from predictionio_tpu.obs.tracing import (
    Span,
    Tracer,
    current_span,
    get_tracer,
    span,
)

__all__ = [
    "CompileTracker",
    "Counter",
    "DeviceSampler",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricRegistry",
    "Objective",
    "SLOMonitor",
    "Span",
    "TRAIN_STEP_BUCKETS",
    "Timeline",
    "Tracer",
    "combine_families",
    "counter_total",
    "current_span",
    "get_registry",
    "get_request_id",
    "get_timeline",
    "get_tracer",
    "merge_payloads",
    "merge_timelines",
    "new_request_id",
    "render_prometheus_families",
    "set_request_id",
    "set_timeline",
    "span",
]
