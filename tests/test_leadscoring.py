"""Lead-scoring template (gallery parity: conversion probability;
the framework's gradient-descent exemplar — optax inside lax.scan,
the whole descent compiled as one program)."""

from __future__ import annotations

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.leadscoring import (
    LeadDataSource,
    LeadDataSourceParams,
    LeadPreparator,
    LeadScoringAlgorithm,
    LeadScoringParams,
    LeadTrainingData,
    leadscoring_engine,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@pytest.fixture(scope="module")
def ctx():
    return ComputeContext.create(batch="lead-test")


def _seed(storage, app_name="LeadApp", n=80):
    """Converted leads have clearly higher engagement; a margin
    separates the clusters so logistic regression must find it."""
    app_id = storage.get_meta_data_apps().insert(App(id=0, name=app_name))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(5)
    batch = []
    for i in range(n):
        # block-assign labels: the k-fold index-modulo split must see
        # both classes in every fold (alternating labels would make
        # fold 0's training data single-class)
        converted = i < n // 2
        base = 8.0 if converted else 2.0
        batch.append(Event(
            event="$set", entity_type="user", entity_id=f"u{i}",
            properties=DataMap({
                "sessions": float(base + rng.normal(0, 0.5)),
                "pages": float(base * 3 + rng.normal(0, 1.0)),
                "minutes": float(base * 5 + rng.normal(0, 2.0)),
                "converted": converted,
            }),
        ))
    events.insert_batch(batch, app_id)
    return app_id


def _train(ctx, storage, algo_params=LeadScoringParams()):
    ds = LeadDataSource(LeadDataSourceParams(app_name="LeadApp"))
    td = ds.read_training(ctx)
    td.sanity_check()
    prepared = LeadPreparator(None).prepare(ctx, td)
    return LeadScoringAlgorithm(algo_params).train(ctx, prepared)


class TestTraining:
    def test_separates_planted_clusters(self, ctx, memory_storage):
        _seed(memory_storage)
        model = _train(ctx, memory_storage)
        algo = LeadScoringAlgorithm(LeadScoringParams())
        hot = algo.predict(
            model, {"features": [8.0, 24.0, 40.0]}
        )
        cold = algo.predict(
            model, {"features": [2.0, 6.0, 10.0]}
        )
        assert hot["converted"] is True and hot["score"] > 0.9
        assert cold["converted"] is False and cold["score"] < 0.1

    def test_scores_are_probabilities(self, ctx, memory_storage):
        _seed(memory_storage)
        model = _train(ctx, memory_storage)
        algo = LeadScoringAlgorithm(LeadScoringParams())
        preds = algo.batch_predict(
            model,
            [{"features": [float(s), float(s * 3), float(s * 5)]}
             for s in range(1, 10)],
        )
        scores = [p["score"] for p in preds]
        assert all(0.0 <= s <= 1.0 for s in scores)
        # monotone in engagement for this 1-direction dataset
        assert scores == sorted(scores)

    def test_empty_batch(self, ctx, memory_storage):
        _seed(memory_storage)
        model = _train(ctx, memory_storage)
        assert LeadScoringAlgorithm(
            LeadScoringParams()
        ).batch_predict(model, []) == []

    def test_sanity_checks(self):
        with pytest.raises(ValueError, match="no labeled leads"):
            LeadTrainingData(
                x=np.zeros((0, 3), np.float32), y=np.zeros(0, np.float32)
            ).sanity_check()
        with pytest.raises(ValueError, match="both converted"):
            LeadTrainingData(
                x=np.ones((4, 3), np.float32), y=np.ones(4, np.float32)
            ).sanity_check()

    def test_nan_features_rejected(self):
        x = np.ones((4, 3), np.float32)
        x[1, 2] = np.nan
        with pytest.raises(ValueError, match="NaN"):
            LeadTrainingData(
                x=x, y=np.array([0, 1, 0, 1], np.float32)
            ).sanity_check()

    def test_string_label_rejected(self, ctx, memory_storage):
        """bool('false') is True — a CSV-derived string label must be
        a loud error, never a silently inverted training signal."""
        app_id = _seed(memory_storage)
        memory_storage.get_events().insert(
            Event(
                event="$set", entity_type="user", entity_id="bad",
                properties=DataMap({
                    "sessions": 1.0, "pages": 1.0, "minutes": 1.0,
                    "converted": "false",
                }),
            ),
            app_id,
        )
        ds = LeadDataSource(LeadDataSourceParams(app_name="LeadApp"))
        with pytest.raises(ValueError, match="must be a boolean"):
            ds.read_training(ctx)

    def test_threshold_is_a_serving_knob(self, ctx, memory_storage):
        """Changing threshold in the deploy-time params must take
        effect WITHOUT retraining (the model only records the
        training-time value for provenance)."""
        _seed(memory_storage)
        model = _train(ctx, memory_storage)  # trained at threshold 0.5
        query = {"features": [8.0, 24.0, 40.0]}  # scores ~0.99
        default = LeadScoringAlgorithm(LeadScoringParams())
        strict = LeadScoringAlgorithm(
            LeadScoringParams(threshold=0.9999)
        )
        assert default.predict(model, query)["converted"] is True
        assert strict.predict(model, query)["converted"] is False


class TestEvaluation:
    def test_kfold_accuracy(self, ctx, memory_storage):
        from predictionio_tpu.core import EngineParams
        from predictionio_tpu.core.evaluation import (
            AverageMetric,
            MetricEvaluator,
        )

        class Accuracy(AverageMetric):
            def calculate_point(self, ei, q, p, a):
                return 1.0 if p["converted"] == a else 0.0

        _seed(memory_storage)
        params = EngineParams(
            data_source=(
                "", LeadDataSourceParams(app_name="LeadApp", eval_k=2)
            ),
            preparator=("", None),
            algorithms=[("logreg", LeadScoringParams())],
        )
        result = MetricEvaluator(Accuracy()).evaluate(
            ctx, leadscoring_engine(), [params]
        )
        assert result.best_score.score >= 0.9  # separable clusters


class TestEngine:
    def test_end_to_end(self, ctx, memory_storage):
        from predictionio_tpu.core import EngineParams
        from predictionio_tpu.core.workflow import (
            load_deployment,
            run_train,
        )

        _seed(memory_storage)
        engine = leadscoring_engine()
        params = EngineParams(
            data_source=("", LeadDataSourceParams(app_name="LeadApp")),
            preparator=("", None),
            algorithms=[("logreg", LeadScoringParams())],
        )
        run_train(
            engine, params, engine_id="lead", ctx=ctx,
            storage=memory_storage,
        )
        _inst, algorithms, models, serving = load_deployment(
            engine, params, engine_id="lead", ctx=ctx,
            storage=memory_storage,
        )
        query = {"features": [8.0, 24.0, 40.0]}
        preds = algorithms[0].batch_predict(models[0], [query])
        out = serving.serve(query, [preds[0]])
        assert out["converted"] is True
