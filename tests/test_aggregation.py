"""EventOp monoid tests (reference LEventAggregatorSpec / PEventAggregatorSpec).

Key property: the fold is order- and grouping-independent, so the
aggregation can be sharded arbitrarily.
"""

import datetime as dt
import itertools
import random

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.aggregation import EventOp, aggregate_properties


def _t(seconds: int) -> dt.datetime:
    return dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(
        seconds=seconds
    )


def _set(eid, props, t):
    return Event(
        event="$set",
        entity_type="user",
        entity_id=eid,
        properties=DataMap(props),
        event_time=_t(t),
    )


def _unset(eid, keys, t):
    return Event(
        event="$unset",
        entity_type="user",
        entity_id=eid,
        properties=DataMap({k: None for k in keys}),
        event_time=_t(t),
    )


def _delete(eid, t):
    return Event(
        event="$delete", entity_type="user", entity_id=eid, event_time=_t(t)
    )


def test_set_last_write_wins():
    out = aggregate_properties(
        [
            _set("u1", {"a": 1, "b": 1}, 0),
            _set("u1", {"a": 2}, 10),
            _set("u1", {"b": 0}, 5),
        ]
    )
    pm = out["u1"]
    assert pm["a"] == 2
    assert pm["b"] == 0
    assert pm.first_updated == _t(0)
    assert pm.last_updated == _t(10)


def test_unset_only_removes_older_sets():
    out = aggregate_properties(
        [
            _set("u1", {"a": 1, "b": 1}, 0),
            _unset("u1", ["a"], 5),
            _set("u1", {"a": 3}, 10),  # re-set after unset → survives
            _unset("u1", ["b"], 1),
        ]
    )
    pm = out["u1"]
    assert pm["a"] == 3
    assert "b" not in pm


def test_delete_covering_latest_set_removes_entity():
    out = aggregate_properties(
        [_set("u1", {"a": 1}, 0), _delete("u1", 5)]
    )
    assert "u1" not in out


def test_delete_then_set_survives():
    out = aggregate_properties(
        [
            _set("u1", {"a": 1, "b": 2}, 0),
            _delete("u1", 5),
            _set("u1", {"a": 9}, 10),
        ]
    )
    pm = out["u1"]
    assert pm["a"] == 9
    assert "b" not in pm  # set before the delete


def test_entity_without_set_is_absent():
    out = aggregate_properties([_unset("u1", ["a"], 0), _delete("u2", 0)])
    assert out == {}


def test_non_special_events_ignored():
    e = Event(
        event="rate",
        entity_type="user",
        entity_id="u1",
        target_entity_type="item",
        target_entity_id="i1",
        event_time=_t(0),
    )
    assert aggregate_properties([e]) == {}


def test_monoid_order_independence():
    events = [
        _set("u1", {"a": 1, "b": 1, "c": 1}, 0),
        _unset("u1", ["b"], 3),
        _set("u1", {"a": 2}, 6),
        _delete("u1", 4),
        _set("u1", {"d": 4}, 8),
        _unset("u1", ["d"], 7),  # older than the set at t=8 → no-op
    ]
    expected = aggregate_properties(events)
    rng = random.Random(0)
    for _ in range(20):
        shuffled = events[:]
        rng.shuffle(shuffled)
        assert aggregate_properties(shuffled) == expected


def test_monoid_grouping_independence():
    events = [
        _set("u1", {"a": 1}, 0),
        _unset("u1", ["a"], 2),
        _set("u1", {"a": 5, "b": 6}, 4),
        _delete("u1", 1),
    ]
    ops = [EventOp.from_event(e) for e in events]
    # fold left-to-right
    seq = ops[0]
    for op in ops[1:]:
        seq = seq.combine(op)
    # fold as balanced tree with identity padding
    tree = (
        ops[0].combine(ops[1]) .combine(ops[2].combine(ops[3]))
    ).combine(EventOp.identity())
    assert seq.to_property_map() == tree.to_property_map()
    assert seq.to_property_map()["a"] == 5


def test_associativity_exhaustive_small():
    events = [
        _set("u1", {"a": 1}, 0),
        _unset("u1", ["a"], 1),
        _set("u1", {"a": 2}, 2),
        _delete("u1", 3),
    ]
    ops = [EventOp.from_event(e) for e in events]
    results = set()
    for perm in itertools.permutations(range(4)):
        acc = EventOp.identity()
        for i in perm:
            acc = acc.combine(ops[i])
        results.add(repr(acc.to_property_map()))
    assert len(results) == 1  # None for every ordering (delete at t=3 wins)
