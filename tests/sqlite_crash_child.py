"""Kill-9 racing-writer child for the sqlite events backend.

Two of these race on ONE database file (WAL mode, per-process
connections); the parent SIGKILLs one mid-commit and asserts that
every event either writer acked is still present when the database
reopens — the concurrent-writer durable-prefix contract behind the
replicated tier's quorum ack (a peer's local commit must survive its
neighbour's crash).

Usage: python tests/sqlite_crash_child.py <db-path> <writer-tag>
"""

from __future__ import annotations

import datetime as dt
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from predictionio_tpu.data import DataMap, Event  # noqa: E402
from predictionio_tpu.data.storage.sqlite import (  # noqa: E402
    SQLiteClient,
    SQLiteEvents,
)

APP_ID = 1


def main() -> int:
    path, tag = sys.argv[1], sys.argv[2]
    backend = SQLiteEvents(SQLiteClient({"PATH": path}))
    backend.init(APP_ID)
    t0 = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
    i = 0
    while True:
        event = Event(
            event="rate",
            entity_type="user",
            entity_id=f"{tag}-{i}",
            properties=DataMap({"writer": tag, "n": i}),
            event_time=t0 + dt.timedelta(seconds=i),
        )
        event_id = backend.insert(event, APP_ID)
        # printed strictly after the commit returned — the ack the
        # parent holds the database to after the SIGKILL
        print(f"ACK {i} {event_id}", flush=True)
        i += 1


if __name__ == "__main__":
    sys.exit(main())
