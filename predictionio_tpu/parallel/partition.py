"""Regex-rule partition engine — PartitionSpecs matched to pytree paths.

The reference blocks its factor RDDs across the cluster with a
partitioner chosen per-RDD (MLlib ALS ``setBlocks``); the TPU-native
equivalent is a **rule table**: an ordered sequence of
``(regex, PartitionSpec)`` pairs matched against each leaf's "/"-joined
pytree path (the DrJAX / fmengine ``match_partition_rules`` idiom —
SNIPPETS.md [1]). One table describes the layout of a whole model or
staged-geometry pytree; the same table derives the ``NamedSharding``
in/out specs of the jitted programs that consume it, so the array
placement and the program contract cannot drift apart.

Rules are matched first-wins with ``re.search``; scalar leaves are never
partitioned (they get ``P()`` without consulting the table); a leaf no
rule matches is a hard error — silent replication of a tensor someone
meant to shard is exactly the bug this engine exists to prevent.

``validate_rules`` checks every axis a table names against a concrete
mesh at staging time; the static ``sharding-spec`` lint rule
(docs/static_analysis.md) performs the same check at review time over
the axis names the project's meshes actually construct.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from predictionio_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    ComputeContext,
    pad_to_multiple,
    record_padded_rows,
)

logger = logging.getLogger(__name__)

#: one partition-rule table: ordered (regex, PartitionSpec) pairs
Rules = Sequence[tuple[str, P]]


# --------------------------------------------------------------------------
# Leaf naming
# --------------------------------------------------------------------------


def _key_name(entry: Any) -> str:
    """One path entry → its name fragment (dict key, attr name, index)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def leaf_path_name(path: tuple) -> str:
    """"/"-joined name of a pytree leaf path (``slabs/0/idx``)."""
    return "/".join(_key_name(p) for p in path)


def tree_leaf_names(tree: Any) -> list[str]:
    """Every leaf's "/"-joined path name, in flatten order — the names
    :func:`match_partition_rules` matches rules against."""
    paths, _ = tree_flatten_with_path(tree)
    return [leaf_path_name(p) for p, _leaf in paths]


# --------------------------------------------------------------------------
# Rule matching
# --------------------------------------------------------------------------


def match_partition_rule(rules: Rules, name: str) -> P:
    """The PartitionSpec the first matching rule assigns to ``name``.

    Raises ``ValueError`` when no rule matches — a table is a complete
    layout description, not a set of hints.
    """
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            return spec
    raise ValueError(
        f"no partition rule matches leaf {name!r}; add a rule (or an "
        f"explicit catch-all) to the table"
    )


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """Pytree of PartitionSpecs matching ``tree``'s structure.

    Each leaf's "/"-joined path is matched against the table
    (first-wins, ``re.search``). Scalar leaves — 0-d or single-element
    arrays, plain Python numbers — are never partitioned and get
    ``P()`` without consulting the table (the fmengine convention).
    """
    paths, treedef = tree_flatten_with_path(tree)
    specs = []
    for path, leaf in paths:
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        specs.append(match_partition_rule(rules, leaf_path_name(path)))
    return tree_unflatten(treedef, specs)


def _spec_axes(spec: P):
    for entry in spec:
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for axis in names:
            if axis is not None:
                yield axis


def validate_rules(rules: Rules, mesh) -> None:
    """Every axis a rule's spec names must exist on ``mesh``.

    GSPMD surfaces a bad axis name deep inside lowering (or silently
    replicates); this fails at staging with the offending rule named.
    """
    axes = set(mesh.axis_names)
    for pattern, spec in rules:
        for axis in _spec_axes(spec):
            if axis not in axes:
                raise ValueError(
                    f"partition rule {pattern!r} names mesh axis "
                    f"{axis!r}, not on mesh axes {sorted(axes)}"
                )


# --------------------------------------------------------------------------
# Placement
# --------------------------------------------------------------------------


def named_shardings(mesh, spec_tree: Any) -> Any:
    """PartitionSpec tree → NamedSharding tree over ``mesh``."""
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_pytree(
    ctx_or_mesh, rules: Rules, tree: Any, *, validate: bool = True
) -> Any:
    """Commit every leaf of ``tree`` to the mesh per the rule table.

    The one-call staging path: match rules → validate axes → one
    ``jax.device_put`` per leaf with the matched ``NamedSharding``.
    Accepts a :class:`ComputeContext` or a bare ``Mesh``.
    """
    mesh = getattr(ctx_or_mesh, "mesh", ctx_or_mesh)
    if validate:
        validate_rules(rules, mesh)
    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tree,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# --------------------------------------------------------------------------
# shard_map (version-portable)
# --------------------------------------------------------------------------


def shard_map(body, *, mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across the jax versions this repo meets.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; the 0.4.x
    line only has ``jax.experimental.shard_map.shard_map(...,
    check_rep=)`` — same semantics, renamed knob. The sharded ALS path
    (and with it every multichip measurement) must run on BOTH: before
    this shim the model-sharded trainer raised ``AttributeError`` on
    0.4.x and the entire sharded test block sat in
    scripts/known_failures.txt, dryrun-green but never measured.
    """
    if hasattr(jax, "shard_map"):
        import inspect

        # discriminate on the kwarg the THIS version accepts, not on
        # attribute presence: the 0.5–0.6 band exposes jax.shard_map
        # with the old check_rep name, so keying on hasattr alone
        # would TypeError on exactly the versions this shim spans
        try:
            params = inspect.signature(jax.shard_map).parameters
        except (TypeError, ValueError):  # C-accelerated / no signature
            params = {}
        if "check_vma" in params:
            kwargs = {"check_vma": check}
        elif "check_rep" in params:
            kwargs = {"check_rep": check}
        else:
            kwargs = {}
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            **kwargs,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check,
    )


# --------------------------------------------------------------------------
# Mesh-from-topology helpers
# --------------------------------------------------------------------------


def topology_mesh_shape(
    n_devices: int, model_parallelism: int = 0
) -> tuple[int, int]:
    """(data, model) mesh shape for ``n_devices``.

    ``model_parallelism=0`` picks the default topology: model axis of 2
    whenever the device count is even (the multichip-dryrun convention
    — factor matrices genuinely split while the data axis keeps the
    slab rows wide), else 1. An explicit value must divide the device
    count.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    model = model_parallelism or (
        2 if n_devices % 2 == 0 and n_devices > 1 else 1
    )
    if model < 1 or n_devices % model:
        raise ValueError(
            f"model_parallelism {model} does not divide {n_devices} "
            "devices"
        )
    return (n_devices // model, model)


def mesh_from_topology(
    n_devices: int | None = None,
    model_parallelism: int = 0,
    batch: str = "",
    devices: Sequence[jax.Device] | None = None,
) -> ComputeContext:
    """ComputeContext over a (data, model) topology.

    ``n_devices=None`` uses every available device; otherwise the first
    ``n_devices`` (the multichip bench sweeps 1→2→4→8 this way on one
    simulated host platform).
    """
    from predictionio_tpu.parallel.mesh import devices_with_timeout

    devs = list(devices if devices is not None else devices_with_timeout())
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return ComputeContext.create(
        batch=batch,
        mesh_shape=topology_mesh_shape(n, model_parallelism),
        devices=devs[:n],
    )


# --------------------------------------------------------------------------
# ALS rule tables (the flagship layout)
# --------------------------------------------------------------------------

#: Model-sharded ALS geometry (docs/parallelism.md "Sharded ALS"):
#: factor matrices row-sliced over ``model`` (each device persistently
#: holds 1/model_parallelism of the rows), slab interaction arrays
#: row-split over the combined (data, model) axes so every chip solves
#: normal equations, the heavy-sub-row owner map split with its slab,
#: and the device-major reassembly permutation split over ``model``.
ALS_SHARDED_RULES: Rules = (
    (r"(^|/)(user|item)_factors$", P(MODEL_AXIS, None)),
    (r"(^|/)owner$", P((DATA_AXIS, MODEL_AXIS))),
    (r"(^|/)(idx|weights|valid)$", P((DATA_AXIS, MODEL_AXIS), None)),
    (r"(^|/)inv_perm$", P(MODEL_AXIS)),
)

#: Replicated-factor ALS geometry (1-D data meshes): factor matrices
#: replicated per device, slab rows split over ``data`` only.
ALS_REPLICATED_RULES: Rules = (
    (r"(^|/)(user|item)_factors$", P()),
    (r"(^|/)(idx|weights|valid|owner)$", P(DATA_AXIS)),
    (r".*", P()),
)


def als_partition_rules(sharded: bool) -> Rules:
    """The ALS rule table for a factor layout (docs/parallelism.md)."""
    return ALS_SHARDED_RULES if sharded else ALS_REPLICATED_RULES


# --------------------------------------------------------------------------
# Serving-side factor staging
# --------------------------------------------------------------------------


def stage_factor_matrix(
    ctx: ComputeContext,
    arr,
    n_real: int | None = None,
) -> tuple[jax.Array, jax.Array | None]:
    """Commit one factor matrix model-sharded; returns
    ``(factors, phantom_mask)``.

    Rows are padded to the model-axis multiple (phantom rows zero) so
    each device holds an equal slice — the serving-side counterpart of
    the trainer's ``row_multiple`` padding. ``phantom_mask`` is a
    device-resident ``[rows] bool`` array, ``True`` on phantom rows
    (``None`` when nothing was padded); serving top-k paths pass it as
    the score mask so a padded row can never surface as a result, even
    if a corrupt artifact gives it nonzero factors. An already
    device-resident array with the right sharding passes through
    without a host round-trip — the unbroken train→serve path.
    """
    spec = match_partition_rule(ALS_SHARDED_RULES, "item_factors")
    sharding = NamedSharding(ctx.mesh, spec)
    n_rows = int(arr.shape[0])
    n_real = n_rows if n_real is None else int(n_real)
    multiple = max(ctx.model_parallelism, 1)
    if isinstance(arr, jax.Array) and not arr.is_deleted():
        if n_rows % multiple:
            raise ValueError(
                f"device-resident factor matrix has {n_rows} rows, not "
                f"a multiple of model_parallelism {multiple}; pad at "
                "training time (train_als row_multiple does)"
            )
        staged = (
            arr
            if arr.sharding == sharding
            else jax.device_put(arr, sharding)
        )
    else:
        padded = pad_to_multiple(np.asarray(arr), multiple, axis=0)
        if padded.shape[0] != n_rows:
            record_padded_rows(
                padded.shape[0] - n_rows, n_rows, multiple
            )
        staged = jax.device_put(padded, sharding)
    if staged.shape[0] <= n_real:
        return staged, None
    mask = np.arange(staged.shape[0]) >= n_real
    mask_sharding = NamedSharding(
        ctx.mesh, match_partition_rule(ALS_SHARDED_RULES, "inv_perm")
    )
    return staged, jax.device_put(mask, mask_sharding)
