"""Scale-out serving tier: a model-aware router over engine replicas.

One ``EngineServer`` process is one GIL and (at most) one accelerator;
the ROADMAP's "millions of users" need N of them. This module is the
front tier that makes N replicas look like one server — the Podracer
shape (PAPERS.md): inference servers are cattle behind a thin router,
and model generations roll through them without a dropped request.

The router consumes exactly the per-replica signals PRs 1–4 built and
nothing else, so any process that mounts the common telemetry surface
(:func:`~predictionio_tpu.serving.http.install_metrics_routes`) can
stand behind it:

* ``GET /healthz`` — alive vs ``draining`` (the SIGTERM drain path);
* ``GET /metrics.json`` — ``pio_warmup_complete`` (a new generation is
  admitted only after every compile bucket warmed) and
  ``pio_server_draining``;
* per-replica :class:`~predictionio_tpu.serving.resilience
  .CircuitBreaker` state from proxy outcomes (5xx / transport errors),
  so a sick replica is excluded and probed back in half-open;
* ``X-PIO-Deadline`` decrements across the router hop, and a
  transport-error/5xx failover retries ONCE against a different
  replica only while budget remains;
* ``X-Request-ID`` / ``X-Parent-Span`` forwarding, so one distributed
  trace spans client → router → replica → store.

Dispatch is least-inflight with consistent-hash affinity as the
tiebreaker: the replica with the least router-tracked in-flight work
wins; ties break on a stable hash ring keyed by ``X-PIO-Affinity``
(falling back to the query body, then the client address), so identical
queries keep landing on the same replica's warm caches without ever
overriding load.

Rolling deploys (``POST /admin/swap``): register a new-generation
replica, admit it only once its warmup gauge reads 1, then drain the
old generation — excluded from selection immediately, in-flight
requests finish, and locally-supervised replicas (registered with a
``pid``) receive SIGTERM so their own graceful drain runs. Zero
requests are dropped; ``scripts/router_smoke.py`` proves it under
replica SIGKILL chaos.

Metrics (docs/scale_out.md): ``pio_router_replica_healthy{replica}``,
``pio_router_inflight{replica}``, ``pio_router_failovers_total``,
``pio_router_requests_total{replica,status}``,
``pio_router_swaps_total{outcome}``.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import signal
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, Iterable

from predictionio_tpu.obs import MetricRegistry, get_registry
from predictionio_tpu.obs import tracing
from predictionio_tpu.obs.context import log_json
from predictionio_tpu.serving import admission, resilience
from predictionio_tpu.serving.http import (
    HTTPError,
    HTTPServer,
    Request,
    Response,
    Router,
    install_metrics_routes,
)

logger = logging.getLogger(__name__)

# -- replica lifecycle states ----------------------------------------------
#: registered, waiting for healthz ok + pio_warmup_complete=1
WARMING = "warming"
#: in the selection pool
HEALTHY = "healthy"
#: excluded from selection; in-flight work finishing (admin retire or
#: the replica's own /healthz says draining)
DRAINING = "draining"
#: probes failing — excluded until a probe succeeds again
UNHEALTHY = "unhealthy"
#: terminal: removed from the active pool by a retire/swap
RETIRED = "retired"

#: affinity header clients may set to pin related queries together
AFFINITY_HEADER = "X-PIO-Affinity"

#: vnodes per replica on the consistent-hash ring — enough that
#: removing one replica only remaps ~1/N of the key space
_RING_VNODES = 32


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


class Replica:
    """One engine-server replica the router knows about."""

    def __init__(
        self,
        replica_id: str,
        url: str,
        generation: str = "",
        pid: int | None = None,
        registry: MetricRegistry | None = None,
        breaker_config: resilience.BreakerConfig | None = None,
    ):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        self.generation = generation
        #: local supervision: a pid lets the router SIGTERM the replica
        #: during a rolling swap so its own graceful drain runs
        self.pid = pid
        self.state = WARMING
        #: set by an admin retire/swap: the drain is STICKY — probes
        #: must not readmit this replica even while its process still
        #: answers ok (the router, not the replica, decided to drain)
        self.admin_draining = False
        #: monotonic instant until which this replica is SOFT-unhealthy:
        #: it answered 503 + Retry-After (its admission controller shed
        #: or it is draining), so it stays in the pool but is
        #: deprioritized — saturation is backpressure, not sickness
        self.saturated_until = 0.0
        self._lock = threading.Lock()
        self._inflight = 0
        self.probe_failures = 0
        self.last_probe: str = "never"
        # NOT the process-global get_breaker map: two routers (or a
        # test building many) must not share breaker state for
        # same-named targets
        self.breaker = resilience.CircuitBreaker(
            f"replica:{replica_id}",
            config=breaker_config,
            registry=registry,
        )
        #: vnode points on the consistent-hash ring, precomputed once —
        #: selection must not pay 32 SHA1s per replica per request
        self.ring_points: tuple[int, ...] = tuple(
            sorted(
                _hash64(f"{replica_id}#{v}".encode())
                for v in range(_RING_VNODES)
            )
        )

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def end(self) -> None:
        with self._lock:
            self._inflight -= 1

    def mark_saturated(self, hint_s: float) -> None:
        """The replica shed with a Retry-After of ``hint_s``: treat it
        as saturated (soft-unhealthy) for that long, clamped to
        [0.05, 5] so a weird hint can't bench a replica for minutes."""
        self.saturated_until = time.monotonic() + min(
            5.0, max(0.05, hint_s)
        )

    @property
    def saturated(self) -> bool:
        return time.monotonic() < self.saturated_until

    def saturation_remaining_s(self) -> float:
        return max(0.0, self.saturated_until - time.monotonic())

    def to_dict(self) -> dict:
        return {
            "id": self.replica_id,
            "url": self.url,
            "generation": self.generation,
            "state": self.state,
            "inflight": self.inflight,
            "breaker": self.breaker.state,
            "saturated": self.saturated,
            "lastProbe": self.last_probe,
            "pid": self.pid,
        }


def _metric_sample(data: dict, name: str, **labels) -> float | None:
    """Pull one sample value out of a ``/metrics.json`` payload."""
    try:
        for sample in data.get(name, {}).get("samples", ()):
            if all(
                sample.get("labels", {}).get(k) == v
                for k, v in labels.items()
            ):
                return float(sample.get("value", sample.get("count")))
    except (AttributeError, TypeError, ValueError):
        return None
    return None


class ServingRouter:
    """HTTP front tier dispatching queries across engine replicas.

    Mount with :meth:`serve` (or the ``pio-tpu router`` CLI verb).
    Thread-safety: the replica map is guarded by one lock; the probe
    loop, proxy handlers, and admin routes all go through it.
    """

    def __init__(
        self,
        replicas: Iterable[Replica] = (),
        *,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        unhealthy_after: int = 2,
        failover_retries: int = 1,
        proxy_timeout_s: float = 30.0,
        drain_poll_s: float = 0.05,
        registry: MetricRegistry | None = None,
        tracer: tracing.Tracer | None = None,
        server_config=None,
        breaker_config: resilience.BreakerConfig | None = None,
    ):
        self._registry = registry if registry is not None else get_registry()
        self._tracer = tracer if tracer is not None else tracing.get_tracer()
        if server_config is None:
            from predictionio_tpu.serving.config import ServerConfig

            server_config = ServerConfig.from_env()
        self._server_config = server_config
        self._breaker_config = breaker_config
        self._probe_interval_s = probe_interval_s
        self._probe_timeout_s = probe_timeout_s
        self._unhealthy_after = max(1, unhealthy_after)
        self._failover_retries = max(0, failover_retries)
        self._proxy_timeout_s = proxy_timeout_s
        self._drain_poll_s = drain_poll_s

        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._retired: list[dict] = []
        #: tied-id tuple -> (sorted vnode points, matching replica ids)
        self._ring_cache: dict[tuple, tuple[list, list]] = {}
        self._swaps: dict[str, dict] = {}
        self._closed = threading.Event()
        # startTime is a display epoch; uptime must come from the
        # monotonic clock — an NTP step would otherwise make uptimeSec
        # jump or go negative
        self._start_time = time.time()  # pio-lint: disable=wall-clock -- display epoch only; uptime uses _start_monotonic
        self._start_monotonic = time.monotonic()

        self._healthy_gauge = self._registry.gauge(
            "pio_router_replica_healthy",
            "1 while the replica is admitted to the selection pool",
            ("replica",),
        )
        self._inflight_gauge = self._registry.gauge(
            "pio_router_inflight",
            "Router-tracked in-flight requests per replica",
            ("replica",),
        )
        self._failovers_total = self._registry.counter(
            "pio_router_failovers_total",
            "Requests retried against a different replica after a "
            "transport error or 5xx",
        )
        self._requests_total = self._registry.counter(
            "pio_router_requests_total",
            "Requests proxied, by replica and upstream status "
            "(status=error for transport failures)",
            ("replica", "status"),
        )
        self._swaps_total = self._registry.counter(
            "pio_router_swaps_total",
            "Rolling generation swaps, by outcome",
            ("outcome",),
        )
        self._shed_total = self._registry.counter(
            "pio_router_shed_total",
            "Requests shed at the router because every healthy "
            "replica advertised saturation (router-level backpressure "
            "— no replica budget burned)",
        )

        for replica in replicas:
            self._install(replica)

        self.router = Router()
        self.router.route("GET", "/", self._status)
        self.router.route("POST", "/queries.json", self._proxy)
        self.router.route("POST", "/batch/queries.json", self._proxy)
        self.router.route("GET", "/admin/replicas", self._admin_list)
        self.router.route("POST", "/admin/replicas", self._admin_register)
        self.router.route(
            "DELETE", "/admin/replicas/<rid>", self._admin_retire
        )
        self.router.route("POST", "/admin/swap", self._admin_swap)
        self.router.route("GET", "/admin/swap/<sid>", self._admin_swap_get)
        install_metrics_routes(
            self.router, self._registry, self._tracer,
            server_config=self._server_config,
        )
        self._http: HTTPServer | None = None
        self._prober = threading.Thread(
            target=self._probe_loop, name="pio-router-probe", daemon=True
        )
        self._prober.start()

    # -- replica registry --------------------------------------------------
    def _install(self, replica: Replica) -> None:
        with self._lock:
            if replica.replica_id in self._replicas:
                raise ValueError(
                    f"replica id {replica.replica_id!r} already registered"
                )
            self._replicas[replica.replica_id] = replica
        rid = replica.replica_id
        self._healthy_gauge.labels(rid).set(0)
        self._inflight_gauge.labels(rid).set_function(
            lambda r=replica: float(r.inflight)
        )
        log_json(
            logger, logging.INFO, "router_replica_registered",
            replica=rid, url=replica.url, generation=replica.generation,
        )

    def add_replica(
        self,
        url: str,
        replica_id: str | None = None,
        generation: str = "",
        pid: int | None = None,
    ) -> Replica:
        """Register a replica; it enters the pool WARMING and is
        admitted by the probe loop once its ``/healthz`` answers ok and
        its ``pio_warmup_complete`` gauge (when exported) reads 1."""
        replica = Replica(
            replica_id or f"r-{uuid.uuid4().hex[:8]}",
            url,
            generation=generation,
            pid=pid,
            registry=self._registry,
            breaker_config=self._breaker_config,
        )
        self._install(replica)
        return replica

    def retire(
        self,
        replica_id: str,
        wait: bool = False,
        on_drained: Callable[[Replica], None] | None = None,
    ) -> bool:
        """Drain a replica out of the pool: selection stops NOW,
        in-flight requests finish, then ``on_drained`` runs (default:
        SIGTERM a locally-supervised replica's ``pid`` so its own
        graceful drain path completes) and the replica is dropped from
        the active map. Returns False when the id is unknown."""
        with self._lock:
            replica = self._replicas.get(replica_id)
            if replica is None:
                return False
            if replica.admin_draining and not wait:
                return True  # a drain is already in flight
            replica.admin_draining = True
            replica.state = DRAINING
        self._healthy_gauge.labels(replica_id).set(0)
        log_json(
            logger, logging.INFO, "router_replica_draining",
            replica=replica_id,
        )

        def _finish():
            while replica.inflight > 0 and not self._closed.is_set():
                time.sleep(self._drain_poll_s)
            try:
                if on_drained is not None:
                    on_drained(replica)
                elif replica.pid:
                    os.kill(replica.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass  # already gone — retiring a dead replica is fine
            except Exception:  # noqa: BLE001 - retire must complete
                logger.exception("retire hook failed for %s", replica_id)
            with self._lock:
                replica.state = RETIRED
                self._replicas.pop(replica_id, None)
                self._retired.append(replica.to_dict())
                del self._retired[:-20]
            # the registry has no series-removal API, so park the dead
            # replica's series at constant 0 — replacing the scrape
            # closure is what lets the Replica (and its breaker) be
            # garbage-collected instead of pinned for process life
            self._inflight_gauge.labels(replica_id).set_function(
                lambda: 0.0
            )
            self._healthy_gauge.labels(replica_id).set(0)
            log_json(
                logger, logging.INFO, "router_replica_retired",
                replica=replica_id,
            )

        if wait:
            _finish()
        else:
            threading.Thread(
                target=_finish,
                name=f"pio-router-retire-{replica_id}",
                daemon=True,
            ).start()
        return True

    def replica_states(self) -> dict[str, str]:
        with self._lock:
            return {
                rid: r.state for rid, r in self._replicas.items()
            }

    # -- health probing ----------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._closed.wait(self._probe_interval_s):
            with self._lock:
                targets = list(self._replicas.values())
            for replica in targets:
                try:
                    self._probe_one(replica)
                except Exception:  # noqa: BLE001 - prober must survive
                    logger.exception(
                        "probe crashed for %s", replica.replica_id
                    )

    def _fetch_json(self, url: str):
        with urllib.request.urlopen(
            urllib.request.Request(url), timeout=self._probe_timeout_s
        ) as resp:
            return resp.status, json.loads(resp.read() or b"null")

    def _probe_one(self, replica: Replica) -> None:
        if replica.state == RETIRED:
            return
        try:
            try:
                status, body = self._fetch_json(replica.url + "/healthz")
            except urllib.error.HTTPError as e:
                status, body = e.code, json.loads(e.read() or b"{}")
            draining = (
                status == 503
                and isinstance(body, dict)
                and body.get("status") == "draining"
            )
            warm = True
            if not draining:
                # scrape warmup + drain gauges; a server that exports
                # neither (non-engine replica) counts as warm
                _, metrics = self._fetch_json(
                    replica.url + "/metrics.json"
                )
                warm_v = _metric_sample(metrics, "pio_warmup_complete")
                warm = warm_v is None or warm_v >= 1.0
                drain_v = _metric_sample(
                    metrics, "pio_server_draining"
                )
                draining = draining or (
                    drain_v is not None and drain_v >= 1.0
                )
        except (OSError, ValueError):
            replica.probe_failures += 1
            replica.last_probe = "unreachable"
            if (
                replica.probe_failures >= self._unhealthy_after
                and replica.state in (HEALTHY, DRAINING)
            ):
                self._set_state(replica, UNHEALTHY)
            return
        replica.probe_failures = 0
        if draining:
            replica.last_probe = "draining"
            # the replica itself says draining (SIGTERM landed on it):
            # stop routing, but an ADMIN-initiated drain stays sticky
            if replica.state in (HEALTHY, WARMING, UNHEALTHY):
                self._set_state(replica, DRAINING)
            return
        replica.last_probe = "ok" if warm else "cold"
        if (
            warm
            and not replica.admin_draining
            and replica.state in (WARMING, UNHEALTHY, DRAINING)
        ):
            # DRAINING→HEALTHY covers a replica that reported draining
            # because its OLD process was exiting and a fresh process
            # now answers ok on the same port (kill + respawn in
            # place). Admin-initiated drains are sticky: the ROUTER
            # decided to drain, so a still-answering process must not
            # probe its way back into the pool mid-retire.
            self._set_state(replica, HEALTHY)

    def _set_state(self, replica: Replica, state: str) -> None:
        with self._lock:
            if replica.state == RETIRED:
                return
            if state == HEALTHY and replica.admin_draining:
                # the probe read admin_draining BEFORE retire() set it
                # (its check runs outside this lock): rechecking here
                # keeps the sticky drain sticky — a readmission racing
                # a retire must lose
                return
            previous, replica.state = replica.state, state
        self._healthy_gauge.labels(replica.replica_id).set(
            1 if state == HEALTHY else 0
        )
        if previous != state:
            log_json(
                logger,
                logging.WARNING if state == UNHEALTHY else logging.INFO,
                "router_replica_state",
                replica=replica.replica_id,
                previous=previous, state=state,
            )

    # -- selection ---------------------------------------------------------
    def _candidates(self, affinity_key: bytes, exclude: set[str]):
        """Healthy replicas in selection order: unsaturated before
        saturated (a replica that just shed is soft-unhealthy — it
        stays available as a last resort but must not absorb traffic
        its own admission controller is refusing), and within each
        band recovering breakers first (their ``allow()`` is the
        half-open probe — skipping them would strand an open breaker
        forever behind healthier peers), then least-inflight with the
        consistent-hash ring breaking ties."""
        with self._lock:
            pool = [
                r
                for r in self._replicas.values()
                if r.state == HEALTHY and r.replica_id not in exclude
            ]
        if not pool:
            return []
        # snapshot the time-dependent saturation flag ONCE per replica:
        # evaluating it in two comprehensions would let a replica whose
        # window expires between them fall into neither band and
        # vanish from the candidate list
        saturated = {r.replica_id: r.saturated for r in pool}
        ordered: list[Replica] = []
        for band in (
            [r for r in pool if not saturated[r.replica_id]],
            [r for r in pool if saturated[r.replica_id]],
        ):
            recovering = [
                r for r in band if r.breaker.state != resilience.CLOSED
            ]
            closed = [
                r for r in band if r.breaker.state == resilience.CLOSED
            ]
            ordered.extend(sorted(recovering, key=lambda r: r.inflight))
            remaining = sorted(closed, key=lambda r: r.inflight)
            while remaining:
                least = remaining[0].inflight
                tied = [r for r in remaining if r.inflight == least]
                if len(tied) == 1:
                    pick = tied[0]
                else:
                    pick = self._ring_pick(tied, affinity_key)
                ordered.append(pick)
                remaining.remove(pick)
        return ordered

    def _ring_pick(
        self, tied: list[Replica], affinity_key: bytes
    ) -> Replica:
        """Consistent-hash pick among tied replicas: the first vnode at
        or after the key's point on the ring. Stable as replicas come
        and go — only ~1/N of the key space remaps per change. The
        merged ring per tied-id set is cached (ids only, so a cached
        entry cannot pin a retired Replica): the steady state — every
        replica idle, all tied — costs one key hash + one bisect per
        request, not a ring rebuild."""
        key = tuple(sorted(r.replica_id for r in tied))
        ring = self._ring_cache.get(key)
        if ring is None:
            merged = sorted(
                (point, r.replica_id)
                for r in tied
                for point in r.ring_points
            )
            ring = ([p for p, _ in merged], [rid for _, rid in merged])
            if len(self._ring_cache) >= 64:
                self._ring_cache.clear()  # membership churn: start over
            self._ring_cache[key] = ring
        points, ids = ring
        by_id = {r.replica_id: r for r in tied}
        idx = bisect.bisect_left(points, _hash64(affinity_key))
        return by_id[ids[idx % len(ids)]]

    def _acquire(
        self, affinity_key: bytes, exclude: set[str]
    ) -> Replica | None:
        """The selected replica with its breaker slot held (the caller
        MUST record success/failure/release on ``replica.breaker``)."""
        for replica in self._candidates(affinity_key, exclude):
            if replica.breaker.allow():
                return replica
        return None

    # -- proxying ----------------------------------------------------------
    def _affinity_key(self, request: Request) -> bytes:
        explicit = request.headers.get(AFFINITY_HEADER)
        if explicit:
            return explicit.encode("utf-8", "replace")
        if request.body:
            return request.body
        return (getattr(request, "client_addr", "") or "").encode()

    def _saturation_hint(self) -> str:
        """Retry-After for a router-level shed: the SOONEST any
        saturated replica expects capacity back (it told us via its
        own Retry-After), floored at 50 ms."""
        with self._lock:
            remaining = [
                r.saturation_remaining_s()
                for r in self._replicas.values()
                if r.state == HEALTHY and r.saturated
            ]
        return admission.format_retry_after(
            min(remaining) if remaining else 0.5
        )

    def _proxy(self, request: Request) -> Response:
        deadline = resilience.get_deadline()
        affinity_key = self._affinity_key(request)
        tried: set[str] = set()
        attempts = 1 + self._failover_retries
        last_failure: str | None = None
        hard_failure = False
        parent = tracing.current_span()
        # router-level shed: when EVERY healthy replica is advertising
        # saturation, forwarding just burns a saturated replica's
        # budget to collect another 503 — answer the backpressure here
        # with the soonest capacity hint. Critical-class traffic still
        # goes through: the replicas' own admission keeps the full
        # limit open for it.
        if request.criticality != admission.CRITICAL:
            # a cheap pool scan, not the full selection ordering (which
            # the first _acquire below would only rebuild)
            with self._lock:
                healthy = [
                    r
                    for r in self._replicas.values()
                    if r.state == HEALTHY
                ]
            if healthy and all(r.saturated for r in healthy):
                self._shed_total.inc()
                return Response(
                    503,
                    {
                        "message": "all replicas are saturated; "
                        "retry after the hinted delay"
                    },
                    headers={
                        "Retry-After": self._saturation_hint(),
                        # nothing was forwarded: replay-safe
                        admission.SHED_HEADER: "saturated",
                    },
                )
        for attempt in range(attempts):
            if deadline is not None and deadline.expired:
                raise resilience.DeadlineExceeded(
                    "budget exhausted routing to a replica"
                )
            replica = self._acquire(affinity_key, tried)
            if replica is None:
                break
            if last_failure is not None:
                # a sibling IS taking over the failed attempt's work —
                # this, not the failure itself, is the failover
                self._failovers_total.inc()
                log_json(
                    logger, logging.WARNING, "router_failover",
                    to=replica.replica_id, error=last_failure,
                )
            tried.add(replica.replica_id)
            span_cm = (
                self._tracer.child(
                    parent,
                    f"router/forward {replica.replica_id}",
                    attributes={
                        "replica": replica.replica_id,
                        "attempt": attempt,
                    },
                )
                if parent is not None and self._tracer.enabled
                else tracing.NOOP
            )
            replica.begin()
            try:
                with span_cm as span:
                    outcome = self._forward(
                        replica, request, deadline, span
                    )
            except BaseException:
                # _forward pairs the breaker verdict with every normal
                # outcome; anything escaping it produced none — release
                # so a half-open probe slot cannot wedge
                replica.breaker.release()
                raise
            finally:
                replica.end()
            if isinstance(outcome, Response):
                return outcome
            # failover-eligible: transport error, retryable 5xx, or a
            # saturation shed (kind distinguishes them — a request that
            # only ever hit saturated replicas becomes a backpressure
            # 503, not a 502)
            kind, last_failure = outcome
            hard_failure = hard_failure or kind == "error"
            if attempt + 1 >= attempts or (
                deadline is not None and deadline.expired
            ):
                break
        if last_failure is not None:
            if not hard_failure:
                # every attempt was answered with a saturation shed:
                # relay the backpressure with the soonest capacity
                # hint. Queries are reads — the replicas' sheds did no
                # work — so the relay is marked replay-safe too.
                self._shed_total.inc()
                return Response(
                    503,
                    {
                        "message": "all tried replicas are saturated; "
                        "retry after the hinted delay"
                    },
                    headers={
                        "Retry-After": self._saturation_hint(),
                        admission.SHED_HEADER: "saturated",
                    },
                )
            # a real failure somewhere — a gateway error the client
            # may retry (the replicas themselves stayed consistent)
            raise HTTPError(502, f"all routed replicas failed: {last_failure}")
        states = set(self.replica_states().values())
        if states and states <= {DRAINING, RETIRED}:
            # drain keeps the small FIXED hint: the pool is rolling,
            # not overloaded, and fresh capacity readmits in about a
            # probe interval, independent of queue state
            return Response(
                503,
                {"message": "all replicas are draining; retry shortly"},
                headers={"Retry-After": "1"},
            )
        return Response(
            503,
            {
                "message": "no healthy replica available"
                + (" (all tried)" if tried else "")
            },
            headers={
                # computed from the router's own recovery cadence: a
                # probe cycle is how fast a replica can possibly be
                # readmitted
                "Retry-After": admission.format_retry_after(
                    2.0 * self._probe_interval_s
                )
            },
        )

    def _forward(
        self,
        replica: Replica,
        request: Request,
        deadline: resilience.Deadline | None,
        span,
    ) -> "Response | tuple[str, str]":
        """One proxied attempt. Returns the upstream Response (success
        — including 4xx/504, which are the replica ANSWERING), or a
        ``(kind, message)`` tuple when the attempt is failover-eligible:
        ``("error", ...)`` for transport errors / retryable 5xx,
        ``("saturated", ...)`` for a 503 carrying Retry-After — the
        replica's admission controller shedding, which is an ANSWER
        for breaker purposes but a reason to try a sibling."""
        url = replica.url + request.path
        req = urllib.request.Request(
            url, data=request.body, method=request.method
        )
        ctype = request.headers.get("Content-Type")
        req.add_header("Content-Type", ctype or "application/json")
        if request.request_id:
            req.add_header("X-Request-ID", request.request_id)
        if request.criticality != admission.DEFAULT:
            # criticality propagates like the deadline, so the
            # replica's admission controller sheds by the CLIENT's
            # class, not the router hop's
            req.add_header(
                admission.CRITICALITY_HEADER, request.criticality
            )
        # nest the replica's root span under the forward span (or the
        # router's root when tracing the forward itself is disabled)
        parent = span if span is not None else tracing.current_span()
        if parent is not None:
            req.add_header(tracing.PARENT_SPAN_HEADER, parent.span_id)
        timeout = self._proxy_timeout_s
        if deadline is not None:
            # reserve a slice of budget for one failover hop, and
            # re-mint the header from what is left NOW so the budget
            # decrements across the router hop
            hop = deadline.reserved(
                min(1.0, self._proxy_timeout_s / 4.0)
            )
            req.add_header(resilience.DEADLINE_HEADER, hop.to_header())
            timeout = hop.cap(timeout)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                body = resp.read()
                status = resp.status
                upstream_headers = resp.headers
                resp_ctype = resp.headers.get(
                    "Content-Type", "application/json"
                )
        except urllib.error.HTTPError as e:
            body = e.read()
            status = e.code
            upstream_headers = e.headers
            resp_ctype = e.headers.get("Content-Type", "application/json")
        except OSError as e:
            replica.breaker.record_failure()
            self._requests_total.labels(replica.replica_id, "error").inc()
            if span is not None:
                span.set("error", str(e))
            return ("error", f"{replica.replica_id}: {e}")
        self._requests_total.labels(
            replica.replica_id, str(status)
        ).inc()
        if span is not None:
            span.set("status", status)
        if status == 503:
            hint = admission.parse_retry_after(
                upstream_headers.get("Retry-After")
                if upstream_headers is not None
                else None
            )
            if hint is not None:
                # cooperative backpressure: the replica ANSWERED —
                # overload (or drain) is not a breaker failure, but it
                # IS a reason to deprioritize it and try a sibling
                replica.mark_saturated(hint)
                replica.breaker.record_success()
                if span is not None:
                    span.set("saturated", True)
                return (
                    "saturated",
                    f"{replica.replica_id}: HTTP 503 (saturated)",
                )
        if status >= 500 and status != 504:
            replica.breaker.record_failure()
            return ("error", f"{replica.replica_id}: HTTP {status}")
        # 2xx/4xx — and 504, the replica answering about an expired
        # budget — are verdicts of health, not failure (a 429
        # fair-share refusal is tenant-specific and forwarded as-is)
        replica.breaker.record_success()
        return Response(status, body, content_type=resp_ctype)

    # -- rolling swap ------------------------------------------------------
    def rolling_swap(
        self,
        url: str,
        generation: str,
        replica_id: str | None = None,
        pid: int | None = None,
        retire: str | list[str] = "others",
        warm_timeout_s: float = 120.0,
        wait: bool = False,
    ) -> dict:
        """Roll the pool to a new model generation without dropping a
        request: register ``url`` WARMING, admit it once healthy AND
        warm (``pio_warmup_complete=1``), then drain the old replicas
        (``retire="others"`` = every active replica of a different
        generation; or an explicit id list). Runs in the background
        unless ``wait=True``; progress lands in the returned record
        (also served at ``GET /admin/swap/<id>``)."""
        new_replica = self.add_replica(
            url, replica_id=replica_id, generation=generation, pid=pid
        )
        swap_id = f"swap-{uuid.uuid4().hex[:8]}"
        record = {
            "id": swap_id,
            "phase": "warming",
            "generation": generation,
            "url": url,
            "replica": new_replica.replica_id,
            "retired": [],
            "error": None,
        }
        with self._lock:
            self._swaps[swap_id] = record
            while len(self._swaps) > 20:
                oldest = next(iter(self._swaps))
                if oldest == swap_id:
                    break
                self._swaps.pop(oldest)

        def _run():
            deadline = time.monotonic() + warm_timeout_s
            while time.monotonic() < deadline and not self._closed.is_set():
                if new_replica.state == HEALTHY:
                    break
                time.sleep(self._drain_poll_s)
            if new_replica.state != HEALTHY:
                record["phase"] = "failed"
                record["error"] = (
                    f"new replica never became healthy+warm within "
                    f"{warm_timeout_s}s (state={new_replica.state}, "
                    f"lastProbe={new_replica.last_probe})"
                )
                self._swaps_total.labels("failed").inc()
                # the old generation keeps serving; pull the dud out
                self.retire(new_replica.replica_id, wait=True)
                return
            record["phase"] = "draining-old"
            if retire == "others":
                with self._lock:
                    victims = [
                        rid
                        for rid, r in self._replicas.items()
                        if rid != new_replica.replica_id
                        and r.generation != generation
                    ]
            else:
                victims = list(retire)
            # drain victims one at a time: capacity never drops by more
            # than one replica mid-swap
            for rid in victims:
                if self.retire(rid, wait=True):
                    record["retired"].append(rid)
            record["phase"] = "done"
            self._swaps_total.labels("ok").inc()
            log_json(
                logger, logging.INFO, "router_swap_done",
                swap=swap_id, generation=generation,
                retired=record["retired"],
            )

        if wait:
            _run()
        else:
            threading.Thread(
                target=_run, name=f"pio-router-{swap_id}", daemon=True
            ).start()
        return record

    # -- routes ------------------------------------------------------------
    def _status(self, request: Request) -> Response:
        with self._lock:
            replicas = [r.to_dict() for r in self._replicas.values()]
        return Response(
            200,
            {
                "status": "alive",
                "service": "router",
                "pid": os.getpid(),
                "startTime": self._start_time,
                "uptimeSec": round(
                    time.monotonic() - self._start_monotonic, 3
                ),
                "replicas": replicas,
                "generations": sorted(
                    {r["generation"] for r in replicas if r["generation"]}
                ),
            },
        )

    def _admin_list(self, request: Request) -> Response:
        self._server_config.check_key(request)
        with self._lock:
            active = [r.to_dict() for r in self._replicas.values()]
            retired = list(self._retired)
        return Response(200, {"replicas": active, "retired": retired})

    def _admin_register(self, request: Request) -> Response:
        self._server_config.check_key(request)
        body = request.json()
        if not isinstance(body, dict) or not body.get("url"):
            raise HTTPError(400, "body must be {'url': ..., ...}")
        pid = body.get("pid")
        if pid is not None and not isinstance(pid, int):
            raise HTTPError(400, "pid must be an integer")
        try:
            replica = self.add_replica(
                str(body["url"]),
                replica_id=body.get("id"),
                generation=str(body.get("generation", "")),
                pid=pid,
            )
        except ValueError as e:
            raise HTTPError(409, str(e)) from None
        return Response(201, replica.to_dict())

    def _admin_retire(self, request: Request) -> Response:
        self._server_config.check_key(request)
        rid = request.path_params["rid"]
        if not self.retire(rid):
            raise HTTPError(404, f"no replica {rid!r}")
        return Response(200, {"id": rid, "state": DRAINING})

    def _admin_swap(self, request: Request) -> Response:
        self._server_config.check_key(request)
        body = request.json()
        if not isinstance(body, dict) or not body.get("url"):
            raise HTTPError(
                400, "body must be {'url': ..., 'generation': ...}"
            )
        pid = body.get("pid")
        if pid is not None and not isinstance(pid, int):
            raise HTTPError(400, "pid must be an integer")
        retire = body.get("retire", "others")
        if retire != "others" and not (
            isinstance(retire, list)
            and all(isinstance(x, str) for x in retire)
        ):
            raise HTTPError(400, "retire must be 'others' or a list of ids")
        try:
            record = self.rolling_swap(
                str(body["url"]),
                generation=str(body.get("generation", "")),
                replica_id=body.get("id"),
                pid=pid,
                retire=retire,
                warm_timeout_s=float(body.get("warmTimeoutS", 120.0)),
            )
        except ValueError as e:
            raise HTTPError(409, str(e)) from None
        return Response(202, record)

    def _admin_swap_get(self, request: Request) -> Response:
        self._server_config.check_key(request)
        record = self._swaps.get(request.path_params["sid"])
        if record is None:
            raise HTTPError(404, "unknown swap id")
        return Response(200, record)

    # -- lifecycle ---------------------------------------------------------
    def serve(self, host: str = "0.0.0.0", port: int = 8100) -> HTTPServer:
        self._http = HTTPServer(
            self.router,
            host=host,
            port=port,
            server_config=self._server_config,
            enforce_key=False,  # queries stay open; /admin/* check_key
            service="router",
            registry=self._registry,
            tracer=self._tracer,
        )
        self._http.add_drain_hook(self.close)
        return self._http

    def close(self) -> None:
        self._closed.set()
        self._prober.join(timeout=5)


def create_router(
    replica_urls: Iterable[str] = (),
    host: str = "0.0.0.0",
    port: int = 8100,
    **kwargs,
) -> tuple[ServingRouter, HTTPServer]:
    """Convenience: a router over ``url`` or ``url#generation``
    strings, bound and ready to ``start()``/``serve_forever()``."""
    router = ServingRouter(**kwargs)
    for i, spec in enumerate(replica_urls):
        url, _, generation = spec.partition("#")
        router.add_replica(url, replica_id=f"r{i}", generation=generation)
    return router, router.serve(host=host, port=port)
