"""Content-hash-keyed per-file findings cache for ``pio-tpu lint``.

The rule set splits cleanly in two:

* **per-file checkers** (``clock``, ``device_sync``, ``donation``,
  ``threads``, ``races``, ``lifecycle`` — each marks itself
  ``PER_FILE = True``): a module's findings are a pure function of
  that module's text. These are cacheable — and they carry the
  expensive per-module models (the thread-root/lockset model alone is
  ~⅓ of a cold run);
* **cross-file checkers** (``locks``, ``jit_retrace``,
  ``sharding_spec``, ``telemetry``, ``wire_contract``): lock-order
  cycles, imported-jit call sites, the mesh-axis, metric-name and
  wire-contract registries all depend on *other* files' content.
  Caching them per file would be unsound, so they run every time.

The engine skips the per-file checkers for every module whose entry is
present and re-runs them only on the misses. Soundness:

* the key is ``sha256(analyzer_salt + file content)`` — the salt
  hashes every ``predictionio_tpu/analysis/**.py`` source plus the
  Python major.minor, so editing any checker (or this file) misses the
  whole cache; a content edit misses that file;
* entries store *raw* findings, before suppression comments are
  applied — the engine applies suppressions on every run, so a cached
  file whose only change is a suppression comment would miss anyway
  (content key), and suppression semantics stay in exactly one place;
* entries are JSON (never pickle) and written atomically (temp file +
  ``os.replace``); an unreadable or schema-mismatched entry is deleted
  and treated as a miss.

The cache directory defaults to ``$XDG_CACHE_HOME/pio-tpu-lint`` (or
``~/.cache/pio-tpu-lint``); ``pio-tpu lint --cache-dir`` overrides it
and ``--no-cache`` disables the cache. Entries untouched for 30 days
are pruned opportunistically.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import time

from predictionio_tpu.analysis.model import Finding
from predictionio_tpu.analysis.source import SourceModule

#: bump to invalidate every existing cache entry on a format change
_SCHEMA = 1

#: prune entries not read/written for this long (best effort)
_PRUNE_AGE_S = 30 * 24 * 3600.0

#: env vars that may change what the analyzer reports (reserved
#: PIO_LINT_* namespace for future knobs) — their values are part of
#: the cache key, so a finding set produced under one configuration
#: never replays under another
_LINT_ENV_PREFIX = "PIO_LINT_"

#: memoized per lint-env tuple (the analyzer sources cannot change
#: within a process, but the env can — tests flip it)
_salt_memo: dict[tuple, str] = {}


def _lint_env() -> tuple:
    return tuple(sorted(
        (k, v)
        for k, v in os.environ.items()
        if k.startswith(_LINT_ENV_PREFIX)
    ))


def default_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "pio-tpu-lint")


def analyzer_salt() -> str:
    """Digest of the analyzer itself: every ``.py`` under
    ``predictionio_tpu/analysis`` plus the Python major.minor (an AST
    produced under 3.11 must not replay under 3.12, where the grammar
    differs — try/except*, new nodes), the lint-relevant ``PIO_LINT_*``
    env, and the cache schema. Editing any checker invalidates the
    whole cache."""
    env = _lint_env()
    cached = _salt_memo.get(env)
    if cached is not None:
        return cached
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    sources: list[str] = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                sources.append(os.path.join(dirpath, name))
    h = hashlib.sha256()
    h.update(
        f"pio-lint-cache/{_SCHEMA}|py{sys.version_info[0]}."
        f"{sys.version_info[1]}|".encode()
    )
    for key, value in env:
        h.update(f"{key}={value}".encode())
        h.update(b"\0")
    for path in sorted(sources):
        h.update(os.path.relpath(path, pkg_root).encode())
        h.update(b"\0")
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            # an unreadable analyzer file: salt on its name only —
            # worst case the cache over-invalidates, never under
            h.update(b"<unreadable>")
        h.update(b"\0")
    _salt_memo[env] = h.hexdigest()
    return _salt_memo[env]


def _finding_to_entry(f: Finding) -> dict:
    # path is NOT stored: the same content may live at another path on
    # load (it is re-homed to the requesting module's rel_path)
    return {
        "rule": f.rule,
        "line": f.line,
        "col": f.col,
        "message": f.message,
        "context": f.context,
        "source": f.source,
    }


def _finding_from_entry(d: dict, rel_path: str) -> Finding:
    return Finding(
        rule=d["rule"],
        path=rel_path,
        line=d["line"],
        col=d["col"],
        message=d["message"],
        context=d["context"],
        source=d["source"],
    )


class LintCache:
    """Per-file findings cache; counts hits/misses for the summary."""

    def __init__(self, cache_dir: str):
        self.dir = cache_dir
        self.hits = 0
        self.misses = 0
        self._salt = analyzer_salt()
        self._usable = True
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError:
            # an unwritable cache dir degrades to cache-off, silently:
            # the lint result must be identical either way
            self._usable = False

    def _entry_path(self, text: str) -> str:
        key = hashlib.sha256(
            (self._salt + "\0").encode() + text.encode()
        ).hexdigest()
        return os.path.join(self.dir, f"{key}.json")

    def load(
        self, mod: SourceModule, checkers: frozenset[str]
    ) -> dict[str, list[Finding]] | None:
        """Cached per-checker findings for this module's content,
        re-homed to its current path; None (counted as a miss) when
        absent, unreadable, or covering a different checker set."""
        if not self._usable:
            self.misses += 1
            return None
        entry = self._entry_path(mod.text)
        try:
            with open(entry, encoding="utf-8") as f:
                data = json.load(f)
            by_checker = {
                name: [
                    _finding_from_entry(d, mod.rel_path)
                    for d in entries
                ]
                for name, entries in data["byChecker"].items()
            }
        except FileNotFoundError:
            self.misses += 1
            return None
        except (OSError, ValueError, KeyError, TypeError):
            # corrupt / truncated / old schema: drop it, re-analyze
            try:
                os.unlink(entry)
            except OSError:
                pass
            self.misses += 1
            return None
        if frozenset(by_checker) != checkers:
            # the per-file checker set changed without an analyzer-
            # source change (should not happen — the salt covers it —
            # but a partial entry must never mask a checker)
            self.misses += 1
            return None
        self.hits += 1
        try:
            os.utime(entry)  # keep hot entries out of the pruner
        except OSError:
            pass
        return by_checker

    def store(
        self, mod: SourceModule, by_checker: dict[str, list[Finding]]
    ) -> None:
        """Write this module's per-checker findings under its content
        key. Best effort: a failed store must never fail the lint."""
        if not self._usable:
            return
        entry = self._entry_path(mod.text)
        payload = {
            "schema": _SCHEMA,
            "byChecker": {
                name: [_finding_to_entry(f) for f in findings]
                for name, findings in by_checker.items()
            },
        }
        try:
            fd, tmp = tempfile.mkstemp(
                dir=self.dir, prefix=".tmp-", suffix=".json"
            )
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(payload, f)
                os.replace(tmp, entry)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            pass

    def prune(self, now: float | None = None) -> None:
        """Drop entries untouched for 30 days (best effort)."""
        if not self._usable:
            return
        now = time.time() if now is None else now
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            p = os.path.join(self.dir, name)
            try:
                if now - os.stat(p).st_mtime > _PRUNE_AGE_S:
                    os.unlink(p)
            except OSError:
                continue

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hitRate": round(self.hits / total, 4) if total else 0.0,
        }
