"""ComputeContext — the SparkContext replacement.

The reference threads a ``SparkContext`` through every controller
signature and creates it per workflow run (``WorkflowContext.scala:25-44``,
app name "PredictionIO <Mode>: <batch>"). Here the equivalent carrier is a
:class:`ComputeContext`: a ``jax.sharding.Mesh`` over the available
devices plus sharding helpers and host-staging utilities. Controllers
receive it as their first argument exactly where the reference passes
``sc``.

Mesh convention (scaling-book style):

* axis ``"data"`` — batch / example / entity-row parallelism (the RDD
  partition analogue; SURVEY.md §2.9 strategy 1);
* axis ``"model"`` — feature / factor / vocabulary sharding (the
  embedding-table tensor-parallel analogue; SURVEY.md §2.9 strategy 2).

Single-chip runs get a 1×1 mesh and every sharding degenerates to
replicated — the same jitted programs run unchanged from 1 chip to a
multi-host slice, which is the whole point of GSPMD.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"


class DeviceInitTimeout(RuntimeError):
    """Backend initialization exceeded PIO_DEVICE_INIT_TIMEOUT_S."""


def devices_with_timeout() -> list:
    """``jax.devices()`` with a hang bound.

    The first call initializes the backend; on a remote-TPU transport a
    wedged tunnel can block it for tens of minutes with no output. Run
    the init in a daemon thread and fail fast with an actionable error
    when it exceeds ``PIO_DEVICE_INIT_TIMEOUT_S`` (0 disables the
    bound). The orphaned thread finishes (or errors) in the background
    — acceptable for a process that is about to report failure anyway.
    (Multi-host coordination has its own bound: jax.distributed's
    ``initialization_timeout``.)
    """
    import os
    import threading

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        # a site plugin may have re-pinned jax_platforms after jax
        # parsed the environment; the user's explicit choice wins
        # (otherwise JAX_PLATFORMS=cpu still dials a remote TPU).
        # updating the config after backends initialized silently
        # no-ops, so detect that state explicitly and say so.
        try:
            from jax._src import xla_bridge as _xb

            already = _xb.backends_are_initialized()
        except Exception:  # noqa: BLE001 - private API moved
            already = False
        if already:
            logger.warning(
                "JAX_PLATFORMS=%s cannot take effect: a backend is "
                "already initialized in this process (a site plugin or "
                "earlier import selected the platform first)",
                env_platforms,
            )
        else:
            jax.config.update("jax_platforms", env_platforms)

    raw = os.environ.get("PIO_DEVICE_INIT_TIMEOUT_S", "300")
    try:
        timeout = float(raw)
    except ValueError:
        logger.warning(
            "PIO_DEVICE_INIT_TIMEOUT_S=%r is not a number; using 300",
            raw,
        )
        timeout = 300.0
    if timeout <= 0:
        return jax.devices()
    result: list = []
    error: list = []

    def _init():
        try:
            result.extend(jax.devices())
        except Exception as exc:  # noqa: BLE001 - re-raised below
            error.append(exc)

    # shutdown contract: joined with a timeout right below; daemon=True
    # because a backend init wedged in the TPU transport cannot be
    # interrupted from Python — on timeout we raise and let the process
    # exit without waiting for it
    t = threading.Thread(target=_init, name="jax-device-init", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise DeviceInitTimeout(
            f"device backend did not initialize within {timeout:.0f}s "
            "(remote TPU transport down?). Set JAX_PLATFORMS=cpu to run "
            "on the host, or raise PIO_DEVICE_INIT_TIMEOUT_S."
        )
    if error:
        raise error[0]
    return result


# backwards-compatible alias (pre-rename imports)
_devices_with_timeout = devices_with_timeout


def pad_to_multiple(
    arr: np.ndarray, multiple: int, axis: int = 0, fill: Any = 0
) -> np.ndarray:
    """Pad ``axis`` up to the next multiple — the fixed-shape boundary
    (SURVEY.md §7 hard-part (a): bucketing/padding at the Preparator)."""
    size = arr.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - size)
    return np.pad(arr, widths, constant_values=fill)


def record_padded_rows(added: int, n_rows: int, parallelism: int) -> None:
    """Telemetry for mesh-padding sites (`shard_rows`, factor staging):
    counts phantom rows added so a workload quietly dominated by
    padding — e.g. an array smaller than the device count — is
    scrape-visible instead of silent."""
    from predictionio_tpu.obs import get_registry

    get_registry().counter(
        "pio_mesh_pad_rows_total",
        "Phantom rows added when padding arrays to a mesh-axis "
        "multiple (shard_rows / sharded factor staging)",
    ).inc(added)
    if n_rows < parallelism:
        logger.warning(
            "padding %d-row array to %d rows to shard over %d "
            "devices — padding exceeds the real data",
            n_rows, n_rows + added, parallelism,
        )


def assert_phantom_rows_zero(
    arr: np.ndarray, n_real: int, what: str = "factors"
) -> None:
    """The phantom-row invariant, asserted once centrally: rows past
    ``n_real`` exist only for mesh-shape padding and must be EXACT
    zeros (the padded normal equations have ``b = 0``, so the solver
    produces 0 — any nonzero phantom means corrupt packing/solve state
    and would score into serving top-k as a ghost entity)."""
    tail = np.asarray(arr)[n_real:]
    if tail.size and np.any(tail != 0):
        bad = int(np.count_nonzero(np.any(tail != 0, axis=-1)))
        raise AssertionError(
            f"phantom-row invariant violated: {bad} padded row(s) of "
            f"{what} past row {n_real} are nonzero"
        )


@dataclasses.dataclass
class ComputeContext:
    """Mesh + sharding helpers threaded through DASE controllers."""

    mesh: Mesh
    batch: str = ""  # run label (reference WorkflowContext app name)

    # -- construction -----------------------------------------------------
    @staticmethod
    def create(
        batch: str = "",
        mesh_shape: Sequence[int] | None = None,
        axis_names: Sequence[str] = (DATA_AXIS, MODEL_AXIS),
        devices: Sequence[jax.Device] | None = None,
    ) -> "ComputeContext":
        """Build a context over the available devices.

        Default mesh: all devices on the ``data`` axis, ``model`` axis of
        size 1 — the right default for the framework's workloads, whose
        first scaling dimension is #entities (SURVEY.md §5). Callers
        (engine variants) may request e.g. ``mesh_shape=(4, 2)`` for
        factor-sharded ALS.

        Backend init is bounded by ``PIO_DEVICE_INIT_TIMEOUT_S``
        (default 300): a wedged remote-TPU transport otherwise blocks
        ``jax.devices()`` indefinitely, hanging every console verb with
        no diagnosis (failure-detection obligation, SURVEY.md §5).
        """
        devs = list(
            devices if devices is not None else devices_with_timeout()
        )
        if mesh_shape is None:
            mesh_shape = (len(devs),) + (1,) * (len(axis_names) - 1)
        if int(np.prod(mesh_shape)) != len(devs):
            raise ValueError(
                f"mesh_shape {tuple(mesh_shape)} does not cover "
                f"{len(devs)} devices"
            )
        device_grid = np.asarray(devs).reshape(tuple(mesh_shape))
        mesh = Mesh(device_grid, tuple(axis_names))
        logger.info(
            "ComputeContext %r: mesh %s over %d %s device(s)",
            batch,
            dict(zip(axis_names, mesh_shape)),
            len(devs),
            devs[0].platform,
        )
        return ComputeContext(mesh=mesh, batch=batch)

    # -- mesh facts -------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return self.mesh.size

    @property
    def data_parallelism(self) -> int:
        return self.mesh.shape.get(DATA_AXIS, 1)

    @property
    def model_parallelism(self) -> int:
        return self.mesh.shape.get(MODEL_AXIS, 1)

    # -- sharding helpers -------------------------------------------------
    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def data_sharded(self) -> NamedSharding:
        """Rows split over the data axis (the RDD-partition analogue)."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @property
    def model_sharded(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(MODEL_AXIS))

    def shard_rows(self, arr: np.ndarray, fill: Any = 0) -> jax.Array:
        """Pad rows to the data-axis multiple and place data-sharded.

        An array smaller than the device count pads up to one row per
        device and still shards (never a silent replicated fallback);
        the added phantom rows are counted in
        ``pio_mesh_pad_rows_total`` and warned about, since a workload
        dominated by padding usually means the mesh is too wide for
        the data."""
        multiple = max(self.data_parallelism, 1)
        padded = pad_to_multiple(arr, multiple, axis=0, fill=fill)
        if padded.shape[0] != arr.shape[0]:
            record_padded_rows(
                padded.shape[0] - arr.shape[0], arr.shape[0], multiple
            )
        return jax.device_put(padded, self.data_sharded)

    def replicate(self, arr: Any) -> jax.Array:
        return jax.device_put(arr, self.replicated)

    def stop(self) -> None:
        """Release compiled-program/array references (reference
        ``sc.stop()``; jax owns the runtime so this is advisory)."""
        jax.clear_caches()
