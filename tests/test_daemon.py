"""Daemon management (start-all / stop-all / daemon verbs) — pidfiles,
stale detection, real background process lifecycle.
Reference analogue: bin/pio-start-all, bin/pio-stop-all, bin/pio-daemon."""

from __future__ import annotations

import json
import os
import subprocess
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.cli import daemon


@pytest.fixture()
def piodir(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    return tmp_path


def _dead_pid() -> int:
    p = subprocess.Popen(["sleep", "0"])
    p.wait()
    return p.pid


class TestPidfiles:
    def test_stopped_when_no_pidfile(self, piodir):
        assert daemon.service_status("eventserver") == ("stopped", None)

    def test_stale_pidfile_detected(self, piodir):
        os.makedirs(os.path.dirname(daemon.pidfile("eventserver")),
                    exist_ok=True)
        dead = _dead_pid()
        with open(daemon.pidfile("eventserver"), "w") as f:
            f.write(str(dead))
        state, pid = daemon.service_status("eventserver")
        assert state == "stale-pidfile" and pid == dead

    def test_stop_removes_stale_pidfile(self, piodir):
        os.makedirs(os.path.dirname(daemon.pidfile("dashboard")),
                    exist_ok=True)
        with open(daemon.pidfile("dashboard"), "w") as f:
            f.write(str(_dead_pid()))
        assert daemon.stop_daemon("dashboard") == "stale pidfile removed"
        assert daemon.service_status("dashboard") == ("stopped", None)

    def test_stop_not_running(self, piodir):
        assert daemon.stop_daemon("adminserver") == "not running"

    def test_garbage_pidfile_is_stopped(self, piodir):
        os.makedirs(os.path.dirname(daemon.pidfile("x")), exist_ok=True)
        with open(daemon.pidfile("x"), "w") as f:
            f.write("not-a-pid")
        assert daemon.service_status("x") == ("stopped", None)


class TestLifecycle:
    """One real daemonized server through the full lifecycle."""

    def test_eventserver_daemon_roundtrip(self, piodir):
        port = 17901
        env = {
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(piodir / "pio.sqlite"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
        }
        pid = daemon.spawn_daemon(
            "eventserver",
            ["eventserver", "--ip", "127.0.0.1", "--port", str(port)],
            env=env,
        )
        try:
            assert daemon.wait_port(
                "127.0.0.1", port, timeout=60.0, pid=pid
            ), open(daemon.logfile("eventserver")).read()[-2000:]
            state, got_pid = daemon.service_status("eventserver")
            assert state == "running" and got_pid == pid
            # the daemon actually serves
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10
            ).read()
            assert json.loads(body)["status"] == "alive"
            # log file captured the boot line
            assert os.path.exists(daemon.logfile("eventserver"))
        finally:
            outcome = daemon.stop_daemon("eventserver")
        assert outcome.startswith("stopped")
        assert daemon.service_status("eventserver") == ("stopped", None)
        assert not daemon.pid_alive(pid)

    def test_double_start_refused(self, piodir, monkeypatch):
        # only manage minipg in this test — the other services would
        # spawn real servers
        monkeypatch.setattr(daemon, "SERVICES", {})
        port = 17902
        pid = daemon.spawn_daemon(
            "minipg",
            ["minipg", "--ip", "127.0.0.1", "--port", str(port)],
        )
        try:
            assert daemon.wait_port(
                "127.0.0.1", port, timeout=60.0, pid=pid
            ), open(daemon.logfile("minipg")).read()[-2000:]
            lines = []
            daemon.start_all(
                ip="127.0.0.1",
                ports={"minipg": port},
                with_minipg=True,
                out=lines.append,
            )
            assert "minipg: already running" in "\n".join(lines)
        finally:
            daemon.stop_daemon("minipg")


class TestStatusAll:
    def test_status_reports_stopped(self, piodir, capsys):
        lines = []
        rc = daemon.status_all(out=lines.append)
        assert rc == 1  # nothing running
        assert any("eventserver: stopped" in ln for ln in lines)


class TestStoreServerDaemon:
    def test_storeserver_daemon_roundtrip(self, piodir):
        """The storeserver rides the same supervisor as minipg: spawn,
        serve, status, stop (reference bin/pio-start-all pattern)."""
        port = 17903
        pid = daemon.spawn_daemon(
            "storeserver",
            ["storeserver", "--ip", "127.0.0.1", "--port", str(port)],
            env={"PIO_FS_BASEDIR": str(piodir)},
        )
        try:
            assert daemon.wait_port(
                "127.0.0.1", port, timeout=60.0, pid=pid
            ), open(daemon.logfile("storeserver")).read()[-2000:]
            state, got_pid = daemon.service_status("storeserver")
            assert state == "running" and got_pid == pid
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10
            ).read()
            assert json.loads(body)["service"] == "storeserver"
        finally:
            outcome = daemon.stop_daemon("storeserver")
        assert outcome.startswith("stopped")
        assert daemon.service_status("storeserver") == ("stopped", None)

    def test_start_all_storeserver_access_key(self, piodir, monkeypatch):
        """`start-all --storeserver-access-key K` must (a) imply the
        storeserver, (b) deliver the key via the environment — never
        argv, where any local user could read it in ps — and (c) yield
        a server that actually enforces the key."""
        monkeypatch.setattr(daemon, "SERVICES", {})
        port = 17904
        lines = []
        rc = daemon.start_all(
            ip="127.0.0.1",
            ports={"storeserver": port},
            with_storeserver=True,
            storeserver_access_key="sekrit",
            out=lines.append,
        )
        try:
            assert rc == 0, "\n".join(lines)
            pid = daemon.read_pid("storeserver")
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                assert b"sekrit" not in f.read()
            # unauthenticated requests are rejected...
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/meta/access_keys",
                    timeout=10,
                )
            assert err.value.code == 401
            # ...and the key opens the door
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/meta/access_keys",
                headers={"Authorization": "Bearer sekrit"},
            )
            assert urllib.request.urlopen(req, timeout=10).status == 200
        finally:
            daemon.stop_daemon("storeserver")
