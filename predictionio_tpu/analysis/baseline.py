"""Baseline file for ``pio-tpu lint`` — the accepted pre-existing
finding set, à la ``scripts/known_failures.txt``.

Format (one finding per line, ``|``-separated; ``#`` comments and blank
lines ignored)::

    rule|path|context|line|source text

Matching ignores the recorded line number: a finding matches a baseline
entry when (rule, path, context, whitespace-normalized source) agree,
so edits elsewhere in the file don't resurrect baselined findings.
Matching is multiset-aware: two identical violations need two entries.
"""

from __future__ import annotations

import dataclasses
from collections import Counter

from predictionio_tpu.analysis.model import Finding, normalize


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    context: str
    line: int
    source: str
    raw_line_no: int  # line in the baseline file itself (diagnostics)

    def fingerprint(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.context, normalize(self.source))


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> list[BaselineEntry]:
    entries: list[BaselineEntry] = []
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, start=1):
            line = raw.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            parts = line.split("|", 4)
            if len(parts) != 5:
                raise BaselineError(
                    f"{path}:{i}: expected "
                    f"'rule|path|context|line|source', got {line!r}"
                )
            rule, fpath, context, lineno, source = parts
            try:
                n = int(lineno)
            except ValueError:
                raise BaselineError(
                    f"{path}:{i}: line field {lineno!r} is not an int"
                ) from None
            entries.append(
                BaselineEntry(rule, fpath, context, n, source, i)
            )
    return entries


def render_baseline(findings: list[Finding]) -> str:
    header = (
        "# pio-tpu lint baseline — accepted pre-existing findings.\n"
        "# Regenerate with: pio-tpu lint --write-baseline\n"
        "# Format: rule|path|context|line|source "
        "(matching ignores the line number)\n"
    )
    rows = [
        f"{f.rule}|{f.path}|{f.context}|{f.line}|{f.source}"
        for f in sorted(findings, key=Finding.sort_key)
    ]
    return header + "".join(row + "\n" for row in rows)


def split_by_baseline(
    findings: list[Finding], entries: list[BaselineEntry]
) -> tuple[list[Finding], list[Finding], list[BaselineEntry]]:
    """(new, baselined, stale) — stale entries match no live finding
    and should be pruned from the baseline file."""
    budget = Counter(e.fingerprint() for e in entries)
    new: list[Finding] = []
    baselined: list[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale: list[BaselineEntry] = []
    for e in entries:
        fp = e.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            stale.append(e)
    return new, baselined, stale
