"""The admission control plane (docs/robustness.md "Overload &
backpressure"): criticality parsing/propagation, the gradient limiter's
adaptation, class-ordered shedding, per-tenant fair share, the computed
Retry-After contract, and the HTTP wiring."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.serving import admission, resilience
from predictionio_tpu.serving.admission import (
    CRITICAL,
    DEFAULT,
    SHEDDABLE,
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
    GradientLimiter,
)
from predictionio_tpu.serving.http import (
    HTTPServer,
    Response,
    Router,
)


@pytest.fixture(autouse=True)
def _clean_context():
    admission.set_criticality(DEFAULT)
    resilience.set_deadline(None)
    yield
    admission.set_criticality(DEFAULT)
    resilience.set_deadline(None)


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestCriticality:
    @pytest.mark.parametrize(
        "raw,expected",
        [
            (None, DEFAULT),
            ("", DEFAULT),
            ("critical", CRITICAL),
            ("CRITICAL", CRITICAL),
            ("  sheddable ", SHEDDABLE),
            ("default", DEFAULT),
            ("vip", DEFAULT),  # unknown never promotes nor refuses
        ],
    )
    def test_parse(self, raw, expected):
        assert admission.parse_criticality(raw) == expected

    def test_contextvar_round_trip(self):
        assert admission.get_criticality() == DEFAULT
        admission.set_criticality(CRITICAL)
        assert admission.get_criticality() == CRITICAL
        admission.set_criticality("junk")  # coerced, never raises
        assert admission.get_criticality() == DEFAULT

    def test_context_manager_restores(self):
        with admission.criticality(SHEDDABLE):
            assert admission.get_criticality() == SHEDDABLE
        assert admission.get_criticality() == DEFAULT

    def test_rank_order(self):
        assert (
            admission.CLASS_RANK[SHEDDABLE]
            < admission.CLASS_RANK[DEFAULT]
            < admission.CLASS_RANK[CRITICAL]
        )


class TestRetryAfterWire:
    def test_format_floors_and_rounds(self):
        assert admission.format_retry_after(0.0) == "0.05"
        assert admission.format_retry_after(1.234) == "1.23"

    @pytest.mark.parametrize(
        "raw,expected",
        [
            ("1", 1.0),
            ("0.25", 0.25),
            (None, None),
            ("", None),
            ("soon", None),
            ("nan", None),
            ("inf", None),
            ("-2", None),
        ],
    )
    def test_parse(self, raw, expected):
        assert admission.parse_retry_after(raw) == expected

    def test_round_trips_own_format(self):
        assert admission.parse_retry_after(
            admission.format_retry_after(0.3)
        ) == 0.3


class TestGradientLimiter:
    def _limiter(self, clock, **overrides):
        cfg = AdmissionConfig(
            initial_limit=overrides.pop("initial_limit", 32.0),
            min_limit=overrides.pop("min_limit", 4.0),
            max_limit=overrides.pop("max_limit", 1024.0),
            **overrides,
        )
        return GradientLimiter(cfg, clock=clock)

    def test_healthy_latency_grows_limit(self):
        clock = _Clock()
        lim = self._limiter(clock)
        start = lim.limit
        for _ in range(50):
            clock.advance(0.01)
            lim.on_sample(0.010)  # flat latency = no queueing signal
        assert lim.limit > start
        assert lim.samples == 50

    def test_inflated_latency_shrinks_limit(self):
        clock = _Clock()
        lim = self._limiter(clock)
        for _ in range(10):
            clock.advance(0.01)
            lim.on_sample(0.010)  # establish a 10ms baseline
        grown = lim.limit
        for _ in range(50):
            clock.advance(0.01)
            lim.on_sample(0.100)  # 10x the baseline: deep queueing
        assert lim.limit < grown

    def test_on_drop_is_multiplicative_and_rate_limited(self):
        clock = _Clock()
        lim = self._limiter(clock, decrease_ratio=0.5)
        before = lim.limit
        lim.on_drop()
        assert lim.limit == pytest.approx(before * 0.5)
        # a storm of drops within the same latency interval is ONE
        # signal, not a slam to the floor
        lim.on_drop()
        lim.on_drop()
        assert lim.limit == pytest.approx(before * 0.5)
        assert lim.drops == 1
        clock.advance(10.0)
        lim.on_drop()
        assert lim.limit == pytest.approx(before * 0.25)

    def test_drop_never_goes_below_min(self):
        clock = _Clock()
        lim = self._limiter(clock, min_limit=8.0, initial_limit=9.0)
        for _ in range(20):
            clock.advance(10.0)
            lim.on_drop()
        assert lim.limit == 8.0

    def test_baseline_window_forgets_old_minimum(self):
        clock = _Clock()
        lim = self._limiter(clock, baseline_window_s=5.0)
        lim.on_sample(0.001)  # one anomalously fast sample
        assert lim.baseline_s() == pytest.approx(0.001)
        # two full window rotations later the old min is gone and the
        # baseline reflects current reality
        for _ in range(4):
            clock.advance(6.0)
            lim.on_sample(0.050)
        assert lim.baseline_s() == pytest.approx(0.050)

    def test_garbage_samples_ignored(self):
        clock = _Clock()
        lim = self._limiter(clock)
        lim.on_sample(-1.0)
        lim.on_sample(float("nan"))
        lim.on_sample(float("inf"))
        assert lim.samples == 0

    def test_initial_clamped_to_floor(self):
        clock = _Clock()
        lim = self._limiter(clock, initial_limit=2.0, min_limit=16.0)
        assert lim.limit == 16.0


def _fixed_controller(limit: float, **cfg_overrides) -> AdmissionController:
    """A controller whose limit cannot move — isolates the shedding
    policy from the limiter dynamics."""
    cfg = AdmissionConfig(
        initial_limit=limit, min_limit=limit, max_limit=limit,
        **cfg_overrides,
    )
    return AdmissionController(
        "test", registry=MetricRegistry(), config=cfg
    )


def _samples(registry: MetricRegistry, name: str) -> list[dict]:
    return registry.to_dict().get(name, {}).get("samples", [])


class TestAdmissionController:
    def test_lowest_class_sheds_first(self):
        ctrl = _fixed_controller(10.0)
        # sheddable fills to 60% of the limit, then sheds
        for _ in range(6):
            ctrl.try_acquire(SHEDDABLE)
        with pytest.raises(AdmissionRejected) as e:
            ctrl.try_acquire(SHEDDABLE)
        assert e.value.status == 503 and e.value.reason == "limit"
        assert e.value.retry_after_s > 0
        # default still has room up to 85%
        ctrl.try_acquire(DEFAULT)
        ctrl.try_acquire(DEFAULT)
        with pytest.raises(AdmissionRejected):
            ctrl.try_acquire(DEFAULT)
        # critical keeps the full limit
        ctrl.try_acquire(CRITICAL)
        ctrl.try_acquire(CRITICAL)
        assert ctrl.inflight == 10
        with pytest.raises(AdmissionRejected):
            ctrl.try_acquire(CRITICAL)

    def test_shed_counter_carries_class_and_reason(self):
        ctrl = _fixed_controller(10.0)
        registry = MetricRegistry()
        ctrl2 = AdmissionController(
            "svc", registry=registry,
            config=AdmissionConfig(
                initial_limit=1.0, min_limit=1.0, max_limit=1.0
            ),
        )
        del ctrl  # only ctrl2's registry is inspected
        ctrl2.try_acquire(CRITICAL)
        with pytest.raises(AdmissionRejected):
            ctrl2.try_acquire(SHEDDABLE)
        rows = _samples(registry, "pio_admission_shed_total")
        assert any(
            r["labels"]
            == {"service": "svc", "class": SHEDDABLE, "reason": "limit"}
            and r["value"] == 1
            for r in rows
        )

    def test_fair_share_refuses_the_hot_tenant_only(self):
        ctrl = _fixed_controller(20.0, fair_pressure=0.5)
        for _ in range(12):
            ctrl.try_acquire(DEFAULT, tenant="hot")
        # under pressure (>10 inflight), a second tenant still gets in
        ctrl.try_acquire(DEFAULT, tenant="cold")
        # the hot tenant is past its equal share (20/2 = 10): 429
        with pytest.raises(AdmissionRejected) as e:
            ctrl.try_acquire(DEFAULT, tenant="hot")
        assert e.value.status == 429 and e.value.reason == "fairshare"
        # critical work from the hot tenant is exempt
        ctrl.try_acquire(CRITICAL, tenant="hot")
        # the cold tenant keeps flowing
        ctrl.try_acquire(DEFAULT, tenant="cold")

    def test_release_outcomes_feed_the_limiter(self):
        ctrl = _fixed_controller(10.0)
        lim = ctrl.limiter
        ctrl.try_acquire(DEFAULT, tenant="t")
        ctrl.release(0.02, admission.OUTCOME_OK, tenant="t")
        assert lim.samples == 1 and ctrl.inflight == 0
        ctrl.try_acquire(DEFAULT)
        ctrl.release(0.02, admission.OUTCOME_DROP)
        assert lim.drops == 1 and lim.samples == 1
        ctrl.try_acquire(DEFAULT)
        ctrl.release(0.02, admission.OUTCOME_IGNORE)
        # no verdict: neither a sample nor a drop
        assert lim.drops == 1 and lim.samples == 1
        assert ctrl.inflight == 0

    def test_retry_after_grows_with_pressure(self):
        ctrl = _fixed_controller(10.0)
        ctrl.limiter.on_sample(0.2)  # ewma 200ms
        idle_hint = ctrl.retry_after_s()
        for _ in range(10):
            ctrl.try_acquire(CRITICAL)
        assert ctrl.retry_after_s() >= idle_hint
        assert 0.05 <= ctrl.retry_after_s() <= 5.0

    def test_from_env_disable_and_floor(self, monkeypatch):
        monkeypatch.setenv("PIO_ADMISSION", "0")
        assert AdmissionController.from_env("x") is None
        monkeypatch.delenv("PIO_ADMISSION")
        ctrl = AdmissionController.from_env(
            "x", registry=MetricRegistry(), min_limit=192.0
        )
        assert ctrl is not None
        # the caller's pipeline floor raises both min and the live limit
        assert ctrl.limiter.limit >= 192.0


class TestAdmissionOverHTTP:
    def _serve(self, handler, controller, registry=None):
        router = Router()
        router.route("GET", "/work", handler)
        router.admission = controller
        http = HTTPServer(
            router, host="127.0.0.1", port=0,
            service="test", registry=registry,
        )
        http.start()
        return http

    def _get(self, url, headers=None):
        req = urllib.request.Request(url, headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read()), resp.headers
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"null"), e.headers

    def test_limit_shed_is_503_with_computed_retry_after(self):
        release = threading.Event()

        def handler(request):
            release.wait(5)
            return Response(200, {"ok": True})

        ctrl = _fixed_controller(2.0)
        http = self._serve(handler, ctrl)
        base = f"http://127.0.0.1:{http.port}"
        results = []
        lock = threading.Lock()

        def hit():
            # critical: may fill the FULL limit of 2 (default would cap
            # at 85%), so exactly two admit and two shed
            out = self._get(
                base + "/work",
                {admission.CRITICALITY_HEADER: "critical"},
            )
            with lock:
                results.append(out)

        threads = [
            threading.Thread(target=hit, daemon=True) for _ in range(4)
        ]
        try:
            for t in threads:
                t.start()
                time.sleep(0.05)  # order admissions before the sheds
            # two admitted (limit 2), two shed while they run
            deadline = time.monotonic() + 5
            while ctrl.inflight < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            release.set()
            for t in threads:
                t.join(10)
            statuses = sorted(r[0] for r in results)
            assert statuses == [200, 200, 503, 503]
            shed_headers = [
                h for s, _b, h in results if s == 503
            ]
            for h in shed_headers:
                hint = admission.parse_retry_after(h.get("Retry-After"))
                assert hint is not None and hint >= 0.05
        finally:
            release.set()
            http.shutdown()

    def test_inflight_released_after_each_request(self):
        ctrl = _fixed_controller(2.0)
        http = self._serve(
            lambda request: Response(200, {"ok": True}), ctrl
        )
        try:
            base = f"http://127.0.0.1:{http.port}"
            for _ in range(5):  # more requests than the limit: all 200
                status, _, _ = self._get(base + "/work")
                assert status == 200
            assert ctrl.inflight == 0
            assert ctrl.limiter.samples == 5
        finally:
            http.shutdown()

    def test_slot_released_when_handler_machinery_raises(self):
        """Regression for the ``acquire-release`` lint finding: an
        exception escaping the handler *machinery* itself (here the
        tracer's span factory — upstream of the dispatch try/except)
        must still release the admission slot. Before the release
        moved into a ``finally``, every such crash leaked a slot until
        the limiter pinned the server shut."""

        class BoomTracer:
            enabled = True

            def trace(self, *args, **kwargs):
                raise RuntimeError("span factory down")

        ctrl = _fixed_controller(2.0)
        router = Router()
        router.route("GET", "/work", lambda request: Response(200, {}))
        router.admission = ctrl
        http = HTTPServer(
            router, host="127.0.0.1", port=0, service="test",
            tracer=BoomTracer(),
        )
        http.start()
        try:
            base = f"http://127.0.0.1:{http.port}"
            for _ in range(3):  # more crashes than the limit of 2
                try:
                    self._get(base + "/work")
                except OSError:
                    pass  # the connection dies mid-crash; that's fine
            assert ctrl.inflight == 0
            # released with NO verdict: a machinery crash says nothing
            # about capacity, so it must not feed the latency signal
            assert ctrl.limiter.samples == 0
        finally:
            http.shutdown()

    def test_telemetry_surface_exempt_from_admission(self):
        ctrl = _fixed_controller(1.0)
        registry = MetricRegistry()
        from predictionio_tpu.serving.http import install_metrics_routes

        router = Router()
        install_metrics_routes(router, registry)
        release = threading.Event()

        def handler(request):
            release.wait(5)
            return Response(200, {"ok": True})

        router.route("GET", "/work", handler)
        router.admission = ctrl
        http = HTTPServer(
            router, host="127.0.0.1", port=0,
            service="test", registry=registry,
        )
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        t = threading.Thread(
            target=lambda: self._get(base + "/work"), daemon=True
        )
        try:
            t.start()
            deadline = time.monotonic() + 5
            while ctrl.inflight < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            # the limit is fully consumed, yet the operator's window
            # stays open
            for path in ("/healthz", "/metrics.json"):
                status, _, _ = self._get(base + path)
                assert status == 200, path
        finally:
            release.set()
            t.join(10)
            http.shutdown()

    def test_criticality_header_installs_contextvar(self):
        seen = []

        def handler(request):
            seen.append(
                (request.criticality, admission.get_criticality())
            )
            return Response(200, {})

        ctrl = _fixed_controller(10.0)
        http = self._serve(handler, ctrl)
        try:
            base = f"http://127.0.0.1:{http.port}"
            self._get(
                base + "/work",
                {admission.CRITICALITY_HEADER: "sheddable"},
            )
            self._get(base + "/work")  # no header: default, not stale
            assert seen == [
                (SHEDDABLE, SHEDDABLE), (DEFAULT, DEFAULT)
            ]
        finally:
            http.shutdown()

    def test_overload_shed_counted_in_http_rejected(self):
        registry = MetricRegistry()
        ctrl = AdmissionController(
            "test", registry=registry,
            config=AdmissionConfig(
                initial_limit=1.0, min_limit=1.0, max_limit=1.0
            ),
        )
        release = threading.Event()

        def handler(request):
            release.wait(5)
            return Response(200, {})

        http = self._serve(handler, ctrl, registry=registry)
        base = f"http://127.0.0.1:{http.port}"
        t = threading.Thread(
            target=lambda: self._get(base + "/work"), daemon=True
        )
        try:
            t.start()
            deadline = time.monotonic() + 5
            while ctrl.inflight < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            status, _, _ = self._get(base + "/work")
            assert status == 503
            rows = _samples(registry, "pio_http_rejected_total")
            assert any(
                r["labels"].get("reason") == "overload"
                and r["value"] == 1
                for r in rows
            )
            # and the gauges the ISSUE names are live
            limits = _samples(registry, "pio_admission_limit")
            assert any(r["value"] == 1.0 for r in limits)
        finally:
            release.set()
            t.join(10)
            http.shutdown()
