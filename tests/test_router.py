"""Scale-out serving router (serving/router.py).

Failover semantics against REAL HTTP replicas (fake handlers on the
framework's own HTTP layer, so drain/healthz behavior is the genuine
article): replica death mid-request, all-replicas-draining, breaker
exclusion + half-open readmission, warmup-gated admission, and the
rolling generation swap's zero-drop guarantee."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.serving import resilience
from predictionio_tpu.serving.http import (
    HTTPError,
    HTTPServer,
    Response,
    Router,
)
from predictionio_tpu.serving.router import (
    DRAINING,
    HEALTHY,
    RETIRED,
    UNHEALTHY,
    WARMING,
    Replica,
    ServingRouter,
)


class FakeReplica:
    """A replica-shaped HTTP server with scriptable behavior."""

    def __init__(self, name: str, warm: float = 1.0):
        self.name = name
        self.warm = warm
        self.fail_next = 0  # respond 500 to this many requests
        self.reset_next = 0  # slam the connection on this many
        self.shed_next = 0  # 503 + Retry-After (admission shed) on
        self.shed_hint = "0.30"  # ... this many, with this hint
        self.delay_s = 0.0
        self.calls = 0
        self.seen_deadlines: list[str | None] = []
        self._lock = threading.Lock()
        router = Router()
        router.route("POST", "/queries.json", self._queries)
        router.route("POST", "/batch/queries.json", self._queries)
        router.route("GET", "/metrics.json", self._metrics)
        self.http = HTTPServer(
            router, host="127.0.0.1", port=0, service=f"replica-{name}"
        )
        self.http.start()
        self.url = f"http://127.0.0.1:{self.http.port}"

    def _queries(self, request) -> Response:
        with self._lock:
            self.calls += 1
            self.seen_deadlines.append(
                request.headers.get(resilience.DEADLINE_HEADER)
            )
            if self.reset_next > 0:
                self.reset_next -= 1
                raise resilience.ChaosReset()  # dies mid-request
            if self.fail_next > 0:
                self.fail_next -= 1
                raise HTTPError(500, "injected replica failure")
            if self.shed_next > 0:
                self.shed_next -= 1
                return Response(
                    503,
                    {"message": "server overloaded"},
                    headers={"Retry-After": self.shed_hint},
                )
        if self.delay_s:
            time.sleep(self.delay_s)
        q = json.loads(request.body)
        return Response(
            200, {"result": q.get("x"), "replica": self.name}
        )

    def _metrics(self, request) -> Response:
        return Response(
            200,
            {
                "pio_warmup_complete": {
                    "type": "gauge",
                    "samples": [{"labels": {}, "value": self.warm}],
                }
            },
        )

    def close(self) -> None:
        self.http.shutdown()


def make_router(*replicas: FakeReplica, **kwargs) -> ServingRouter:
    kwargs.setdefault("probe_interval_s", 0.05)
    kwargs.setdefault("probe_timeout_s", 2.0)
    kwargs.setdefault("unhealthy_after", 1)
    kwargs.setdefault("registry", MetricRegistry())
    kwargs.setdefault(
        "breaker_config",
        resilience.BreakerConfig(failure_threshold=2, reset_after_s=0.25),
    )
    router = ServingRouter(**kwargs)
    for rep in replicas:
        router.add_replica(rep.url, replica_id=rep.name)
    return router


def wait_for(cond, timeout_s: float = 10.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


@pytest.fixture()
def pair():
    """Two healthy fake replicas behind a bound router."""
    a, b = FakeReplica("a"), FakeReplica("b")
    router = make_router(a, b, failover_retries=1)
    http = router.serve(host="127.0.0.1", port=0)
    http.start()
    assert wait_for(
        lambda: set(router.replica_states().values()) == {HEALTHY}
    ), router.replica_states()
    try:
        yield router, http, a, b
    finally:
        router.close()
        http.shutdown()
        a.close()
        b.close()


def post(base: str, path: str, body, headers=None, timeout=10):
    req = urllib.request.Request(
        f"{base}{path}",
        data=json.dumps(body).encode(),
        headers=headers or {},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), e.headers


def counter_value(registry: MetricRegistry, name: str, **labels):
    data = registry.to_dict()
    for sample in data.get(name, {}).get("samples", ()):
        if all(sample["labels"].get(k) == v for k, v in labels.items()):
            return sample.get("value", sample.get("count"))
    return None


class TestFailover:
    def test_replica_death_mid_request_retries_sibling(self, pair):
        """The connection is severed MID-REQUEST (after the replica
        accepted it); the router retries the sibling inside the
        deadline budget and the client sees a clean 200."""
        router, http, a, b = pair
        a.reset_next = 5
        b.reset_next = 0
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 7},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 200 and body["result"] == 7
        assert body["replica"] == "b"
        assert counter_value(
            router._registry, "pio_router_failovers_total"
        ) == 1

    def test_failover_decrements_deadline_budget(self, pair):
        router, http, a, b = pair
        a.reset_next = 1
        b.reset_next = 1  # both die: retries exhausted -> 502
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 502
        assert "failed" in body["message"]
        # both replicas saw a decremented (never amplified) budget
        seen = [
            float(h) for h in a.seen_deadlines + b.seen_deadlines if h
        ]
        assert seen and all(v <= 10000 for v in seen)

    def test_expired_deadline_rejected_before_routing(self, pair):
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-PIO-Deadline": "0"},
        )
        assert status == 504
        assert a.calls == 0 and b.calls == 0

    def test_4xx_passes_through_without_failover(self, pair):
        """A replica ANSWERING with 4xx is health, not failure — the
        router must not mask it or burn a retry."""
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(base, "/nope.json", {"x": 1})
        assert status == 404  # router's own router: no such route
        a.fail_next = 0
        # upstream 404 via batch route patched to 400: use bad JSON body
        req = urllib.request.Request(
            f"{base}/queries.json", data=b"{not json",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
        assert counter_value(
            router._registry, "pio_router_failovers_total"
        ) in (None, 0)


class TestDraining:
    def test_all_replicas_draining_503_retry_after(self, pair):
        router, http, a, b = pair
        a.http.begin_drain()
        b.http.begin_drain()
        assert wait_for(
            lambda: set(router.replica_states().values()) == {DRAINING}
        ), router.replica_states()
        base = f"http://127.0.0.1:{http.port}"
        status, body, headers = post(base, "/queries.json", {"x": 1})
        assert status == 503
        assert headers.get("Retry-After")
        assert "draining" in body["message"]

    def test_draining_replica_excluded_but_sibling_serves(self, pair):
        router, http, a, b = pair
        a.http.begin_drain()
        assert wait_for(
            lambda: router.replica_states()["a"] == DRAINING
        )
        base = f"http://127.0.0.1:{http.port}"
        for i in range(5):
            status, body, _ = post(base, "/queries.json", {"x": i})
            assert status == 200 and body["replica"] == "b"


class TestBreaker:
    def test_open_breaker_excluded_then_readmitted_half_open(self):
        # own router: a WIDE reset window (vs the pair fixture's
        # 0.25s) so the exclusion phase cannot race into half-open on
        # a slow runner and see a legitimate probe hit the replica
        a, b = FakeReplica("a"), FakeReplica("b")
        router = make_router(
            a, b, failover_retries=1,
            breaker_config=resilience.BreakerConfig(
                failure_threshold=2, reset_after_s=1.5
            ),
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert wait_for(
                lambda: set(router.replica_states().values())
                == {HEALTHY}
            )
            # trip a's breaker (threshold 2); each 500 fails over to b
            a.fail_next = 10
            for i in range(3):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200 and body["replica"] == "b"
            with router._lock:
                breaker_a = router._replicas["a"].breaker
            assert breaker_a.state == resilience.OPEN
            calls_while_open = a.calls
            for i in range(5):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200 and body["replica"] == "b"
            # open breaker: a never even saw a request
            assert a.calls == calls_while_open
            # recovery: past the reset window the next request is a's
            # half-open probe (recovering replicas are probed first)
            a.fail_next = 0
            time.sleep(1.6)
            served_by_a = False
            for i in range(10):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200
                if body["replica"] == "a":
                    served_by_a = True
                    break
            assert served_by_a, "recovered replica never probed back in"
            assert breaker_a.state == resilience.CLOSED
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_failed_half_open_probe_fails_over_and_reopens(self, pair):
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        a.fail_next = 100
        for i in range(3):
            post(base, "/queries.json", {"x": i})
        with router._lock:
            breaker_a = router._replicas["a"].breaker
        assert breaker_a.state == resilience.OPEN
        time.sleep(0.3)  # reset window elapses; a STILL broken
        status, body, _ = post(base, "/queries.json", {"x": 1})
        assert status == 200 and body["replica"] == "b"
        assert breaker_a.state == resilience.OPEN


class TestAdmission:
    def test_cold_replica_not_admitted_until_warm(self):
        rep = FakeReplica("cold", warm=0.0)
        router = make_router(rep)
        try:
            time.sleep(0.3)
            assert router.replica_states() == {"cold": WARMING}
            rep.warm = 1.0
            assert wait_for(
                lambda: router.replica_states() == {"cold": HEALTHY}
            )
        finally:
            router.close()
            rep.close()

    def test_dead_replica_marked_unhealthy_then_readmitted(self):
        rep = FakeReplica("flappy")
        router = make_router(rep)
        try:
            assert wait_for(
                lambda: router.replica_states() == {"flappy": HEALTHY}
            )
            port = rep.http.port
            rep.http.shutdown()
            assert wait_for(
                lambda: router.replica_states() == {"flappy": UNHEALTHY}
            )
            # a new process binds the same port (kill + respawn in place)
            rep2 = FakeReplica("flappy2")
            # point the router's replica at the new port by rebinding
            # the URL (same effect as a respawn on the original port,
            # without racing the OS for the freed port number)
            with router._lock:
                router._replicas["flappy"].url = rep2.url
            assert wait_for(
                lambda: router.replica_states() == {"flappy": HEALTHY}
            )
            rep2.close()
        finally:
            router.close()
            rep.close()

    def test_no_replicas_503(self):
        router = make_router()
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            status, body, headers = post(
                f"http://127.0.0.1:{http.port}", "/queries.json", {"x": 1}
            )
            assert status == 503 and headers.get("Retry-After")
        finally:
            router.close()
            http.shutdown()


class TestSelection:
    @staticmethod
    def _router():
        # no probe loop: these tests hand-set replica states and the
        # prober would flip unreachable URLs to unhealthy mid-assert
        return make_router(probe_interval_s=999.0)

    def _replicas(self, router, n):
        return [
            router.add_replica(
                f"http://127.0.0.1:{9000 + i}", replica_id=f"r{i}"
            )
            for i in range(n)
        ]

    def test_least_inflight_wins(self):
        router = self._router()
        try:
            reps = self._replicas(router, 3)
            for r in reps:
                r.state = HEALTHY
            reps[0]._inflight = 5
            reps[1]._inflight = 1
            reps[2]._inflight = 5
            picked = router._candidates(b"key", set())[0]
            assert picked.replica_id == "r1"
        finally:
            router.close()

    def test_affinity_breaks_ties_stably(self):
        router = self._router()
        try:
            reps = self._replicas(router, 4)
            for r in reps:
                r.state = HEALTHY
            first = router._candidates(b"user-42", set())[0]
            for _ in range(10):
                assert (
                    router._candidates(b"user-42", set())[0]
                    is first
                )
            # different keys spread across replicas
            picks = {
                router._candidates(f"u{i}".encode(), set())[0].replica_id
                for i in range(50)
            }
            assert len(picks) > 1
        finally:
            router.close()

    def test_tenant_keyed_affinity(self):
        """Pooled multi-tenant serving: accessKey/X-PIO-Tenant pins a
        tenant's traffic to one replica so its model stays hot in ONE
        pool; an explicit affinity header still wins."""
        from predictionio_tpu.serving.http import Request

        def req(query=None, headers=None, body=b""):
            return Request(
                "POST", "/queries.json", query or {}, headers or {},
                body, {},
            )

        router = self._router()
        try:
            key = router._affinity_key(
                req(query={"accessKey": "alice"}, body=b"{'x': 1}")
            )
            assert key == b"tenant:alice"
            # header spelling resolves identically → same ring point
            assert router._affinity_key(
                req(headers={"X-PIO-Tenant": "alice"}, body=b"other")
            ) == key
            # explicit affinity beats the tenant
            assert router._affinity_key(
                req(
                    query={"accessKey": "alice"},
                    headers={"X-PIO-Affinity": "u9"},
                )
            ) == b"u9"
            # no tenant → body hash fallback unchanged
            assert router._affinity_key(req(body=b"abc")) == b"abc"
        finally:
            router.close()

    def test_ring_stability_across_membership_change(self):
        """Removing one tied replica only remaps keys that hashed to
        it — every other key keeps its replica (consistent hashing,
        not modulo)."""
        router = self._router()
        try:
            reps = self._replicas(router, 4)
            for r in reps:
                r.state = HEALTHY
            keys = [f"key-{i}".encode() for i in range(80)]
            before = {
                k: router._candidates(k, set())[0].replica_id
                for k in keys
            }
            victim = "r2"
            with router._lock:
                router._replicas.pop(victim)
            after = {
                k: router._candidates(k, set())[0].replica_id
                for k in keys
            }
            moved = [
                k for k in keys
                if before[k] != victim and after[k] != before[k]
            ]
            assert not moved, f"{len(moved)} unrelated keys remapped"
        finally:
            router.close()


class TestRollingSwap:
    def test_swap_zero_dropped_inflight(self):
        """An in-flight request on the OLD generation finishes 200
        while the swap drains it; the new generation takes over."""
        old = FakeReplica("old")
        old.delay_s = 0.4
        router = make_router(old, failover_retries=0)
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        new = FakeReplica("new")
        try:
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            results = {}

            def slow_query():
                results["slow"] = post(
                    base, "/queries.json", {"x": 5}, timeout=15
                )

            t = threading.Thread(target=slow_query)
            t.start()
            assert wait_for(lambda: old.calls >= 1, timeout_s=5)
            drained = []
            record = router.rolling_swap(
                new.url,
                generation="g2",
                replica_id="new",
                retire="others",
                wait=True,
            )
            t.join(timeout=15)
            status, body, _ = results["slow"]
            assert status == 200 and body["result"] == 5
            assert record["phase"] == "done"
            assert record["retired"] == ["old"]
            assert router.replica_states() == {"new": HEALTHY}
            # the new generation serves now
            status, body, _ = post(base, "/queries.json", {"x": 9})
            assert status == 200 and body["replica"] == "new"
        finally:
            router.close()
            http.shutdown()
            old.close()
            new.close()

    def test_swap_aborts_when_new_replica_never_warms(self):
        old = FakeReplica("old")
        cold = FakeReplica("cold", warm=0.0)
        router = make_router(old)
        try:
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            record = router.rolling_swap(
                cold.url,
                generation="g2",
                replica_id="cold",
                warm_timeout_s=0.5,
                wait=True,
            )
            assert record["phase"] == "failed"
            assert "never became healthy" in record["error"]
            # the old generation is untouched; the dud is gone
            assert router.replica_states() == {"old": HEALTHY}
        finally:
            router.close()
            old.close()
            cold.close()

    def test_swap_retires_old_via_sigterm_pid(self):
        """A locally-supervised old replica (registered with a pid)
        receives SIGTERM when its drain completes."""
        import os
        import signal as _signal

        received = []
        handler = _signal.signal(
            _signal.SIGTERM, lambda s, f: received.append(s)
        )
        old = FakeReplica("old")
        new = FakeReplica("new")
        router = make_router()
        try:
            router.add_replica(
                old.url, replica_id="old", pid=os.getpid()
            )
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            record = router.rolling_swap(
                new.url, generation="g2", replica_id="new", wait=True
            )
            assert record["phase"] == "done"
            assert received == [_signal.SIGTERM]
        finally:
            _signal.signal(_signal.SIGTERM, handler)
            router.close()
            old.close()
            new.close()


class TestAdminRoutes:
    @pytest.fixture()
    def gated(self):
        from predictionio_tpu.serving.config import ServerConfig

        rep = FakeReplica("a")
        router = make_router(
            server_config=ServerConfig(
                key_auth_enforced=True, access_key="sekrit"
            ),
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            yield router, f"http://127.0.0.1:{http.port}", rep
        finally:
            router.close()
            http.shutdown()
            rep.close()

    def test_register_requires_key(self, gated):
        router, base, rep = gated
        status, _, _ = post(base, "/admin/replicas", {"url": rep.url})
        assert status == 401
        status, body, _ = post(
            base, "/admin/replicas",
            {"id": "a", "url": rep.url, "generation": "g1"},
            headers={"X-PIO-Server-Key": "sekrit"},
        )
        assert status == 201 and body["id"] == "a"
        assert wait_for(lambda: router.replica_states() == {"a": HEALTHY})
        # queries stay open (no key needed)
        status, body, _ = post(base, "/queries.json", {"x": 3})
        assert status == 200 and body["result"] == 3

    def test_duplicate_id_conflict(self, gated):
        router, base, rep = gated
        key = {"X-PIO-Server-Key": "sekrit"}
        status, _, _ = post(
            base, "/admin/replicas", {"id": "a", "url": rep.url},
            headers=key,
        )
        assert status == 201
        status, body, _ = post(
            base, "/admin/replicas", {"id": "a", "url": rep.url},
            headers=key,
        )
        assert status == 409

    def test_spawnerless_urlless_swap_is_a_400_misconfiguration(self):
        """A swap body with no url on a router without --spawn-replica
        is a permanent misconfiguration: it must answer 400 so the
        trainer fails fast, not 409 (its retry-shortly signal — which
        would stall every promotion for the full promote budget)."""
        a = FakeReplica("a")
        router = make_router(a)
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            status, body, _ = post(
                base, "/admin/swap",
                {"generation": "g2", "token": "gen-g2"},
            )
            assert status == 400
            assert "spawn" in body["message"]
            # the token was never reserved by the refused request
            assert "gen-g2" not in router._swap_tokens
        finally:
            router.close()
            http.shutdown()
            a.close()

    def test_retire_via_delete(self, gated):
        router, base, rep = gated
        key = {"X-PIO-Server-Key": "sekrit"}
        post(base, "/admin/replicas", {"id": "a", "url": rep.url},
             headers=key)
        assert wait_for(lambda: router.replica_states() == {"a": HEALTHY})
        req = urllib.request.Request(
            f"{base}/admin/replicas/a", method="DELETE",
            headers=key,
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
        assert wait_for(lambda: router.replica_states() == {})
        # listed as retired
        req = urllib.request.Request(
            f"{base}/admin/replicas", headers=key
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            listing = json.loads(resp.read())
        assert [r["id"] for r in listing["retired"]] == ["a"]
        assert listing["retired"][0]["state"] == RETIRED


class TestTracing:
    def test_forward_joins_the_request_trace(self, pair):
        """The replica's root span carries the SAME trace ID the
        client sent, parented under the router's forward span."""
        from predictionio_tpu.obs import tracing

        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        tracer = tracing.get_tracer()
        status, _, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-Request-ID": "trace-router-1"},
        )
        assert status == 200
        spans = [
            s
            for t in tracer.to_dict().get("traces", [])
            for s in t.get("spans", [])
            if s.get("traceId") == "trace-router-1"
        ]
        names = {s["name"] for s in spans}
        assert any(n.startswith("router ") for n in names), names
        assert any(n.startswith("router/forward") for n in names), names
        # the replica runs in-process here too, so its root span landed
        # in the same process tracer under the same trace id
        assert any(n.startswith("replica-") for n in names), names


class TestSaturationBackpressure:
    """A replica shedding 503 + Retry-After is soft-unhealthy, not
    sick: breaker success, failover to a sibling, deprioritized in
    selection, and a router-level shed once EVERYONE is saturated
    (docs/robustness.md "Overload & backpressure")."""

    def test_shed_fails_over_without_breaker_failure(self, pair):
        router, http, a, b = pair
        a.shed_next = 5
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 3},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 200 and body["replica"] == "b"
        with router._lock:
            rep_a = router._replicas["a"]
        # the shed marked it saturated for the hinted window, and its
        # breaker saw an ANSWER, not a failure
        assert rep_a.saturated
        assert rep_a.breaker.state == resilience.CLOSED
        # while saturated, traffic prefers the sibling outright
        for _ in range(3):
            status, body, _ = post(base, "/queries.json", {"x": 4})
            assert status == 200 and body["replica"] == "b"

    def test_all_saturated_sheds_at_router_with_soonest_hint(self, pair):
        router, http, a, b = pair
        a.shed_next = 2
        b.shed_next = 2
        base = f"http://127.0.0.1:{http.port}"
        status, body, headers = post(
            base, "/queries.json", {"x": 5},
            headers={"X-PIO-Deadline": "10000"},
        )
        # both replicas answered a shed: the router relays the
        # backpressure (503 + computed hint), never a 502
        assert status == 503
        hint = headers.get("Retry-After")
        assert hint is not None and 0 < float(hint) <= 5.0
        assert "saturated" in body["message"]
        assert counter_value(
            router._registry, "pio_router_shed_total"
        ) == 1
        # next request, with both replicas still inside their hint
        # window: shed at the router BEFORE burning a replica's budget
        calls_before = a.calls + b.calls
        status, _, headers = post(base, "/queries.json", {"x": 6})
        assert status == 503 and headers.get("Retry-After")
        assert a.calls + b.calls == calls_before
        # once the hint window passes, traffic flows again
        assert wait_for(
            lambda: post(base, "/queries.json", {"x": 7})[0] == 200,
            timeout_s=5,
        )

    def test_critical_class_bypasses_router_shed(self, pair):
        from predictionio_tpu.serving import admission

        router, http, a, b = pair
        a.shed_next = 1
        b.shed_next = 1
        base = f"http://127.0.0.1:{http.port}"
        # saturate both marks
        post(base, "/queries.json", {"x": 1},
             headers={"X-PIO-Deadline": "10000"})
        with router._lock:
            assert all(r.saturated for r in router._replicas.values())
        # a critical request is still FORWARDED (the replicas' own
        # admission keeps the full limit open for it) — and they are
        # no longer shedding, so it serves
        calls_before = a.calls + b.calls
        status, _, _ = post(
            base, "/queries.json", {"x": 2},
            headers={admission.CRITICALITY_HEADER: "critical"},
        )
        assert status == 200
        assert a.calls + b.calls > calls_before

    def test_criticality_header_forwarded_to_replica(self, pair):
        from predictionio_tpu.serving import admission

        router, http, a, b = pair
        seen = []
        orig_a, orig_b = a._queries, b._queries

        def spy(rep_orig):
            def _h(request):
                seen.append(
                    request.headers.get(admission.CRITICALITY_HEADER)
                )
                return rep_orig(request)
            return _h

        a._queries = spy(orig_a)
        b._queries = spy(orig_b)
        # rebuild routes to pick up the spies
        for rep in (a, b):
            rep.http.router._routes = []
            rep.http.router.route("POST", "/queries.json", rep._queries)
            rep.http.router.route("GET", "/metrics.json", rep._metrics)
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(
            base, "/queries.json", {"x": 9},
            headers={admission.CRITICALITY_HEADER: "sheddable"},
        )
        assert status == 200
        assert seen == ["sheddable"]

    def test_tenant_forwarded_to_replica(self, pair):
        """Regression for the ``wire-header`` lint finding: the
        replica's per-tenant fair share read ``X-PIO-Tenant`` but no
        hop ever set it — routed traffic was all anonymous, so one
        tenant could starve the rest THROUGH the router. The router
        now forwards the tenant, resolved like the admission gate
        resolves it: accessKey query param first, then the header."""
        from predictionio_tpu.serving import admission

        router, http, a, b = pair
        seen = []
        orig_a, orig_b = a._queries, b._queries

        def spy(rep_orig):
            def _h(request):
                seen.append(
                    request.headers.get(admission.TENANT_HEADER)
                )
                return rep_orig(request)
            return _h

        a._queries = spy(orig_a)
        b._queries = spy(orig_b)
        for rep in (a, b):
            rep.http.router._routes = []
            rep.http.router.route("POST", "/queries.json", rep._queries)
            rep.http.router.route("GET", "/metrics.json", rep._metrics)
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(
            base, "/queries.json", {"x": 1},
            headers={admission.TENANT_HEADER: "acme"},
        )
        assert status == 200
        status, _, _ = post(
            base, "/queries.json?accessKey=k-42", {"x": 2}
        )
        assert status == 200
        # an accessKey outranks the header, mirroring the gate
        status, _, _ = post(
            base, "/queries.json?accessKey=k-42", {"x": 3},
            headers={admission.TENANT_HEADER: "acme"},
        )
        assert status == 200
        assert seen == ["acme", "k-42", "k-42"]

    def test_empty_pool_hint_is_computed_not_hardcoded(self):
        router = make_router()  # no replicas at all
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            base = f"http://127.0.0.1:{http.port}"
            status, _, headers = post(base, "/queries.json", {"x": 1})
            assert status == 503
            hint = headers.get("Retry-After")
            # 2x the probe interval (0.05 in tests) — the recovery
            # cadence, not the legacy constant "1"
            assert hint == "0.10"
        finally:
            router.close()
            http.shutdown()


# -- fleet control plane ----------------------------------------------------


class GateReplica(FakeReplica):
    """FakeReplica whose predictions carry only model-comparable
    content — the fleet gate compares bodies across replica processes,
    so the fixture must not leak its own name into the divergence."""

    def __init__(self, name: str, warm: float = 1.0, offset: int = 0):
        self.offset = offset
        self.nan_result = False
        super().__init__(name, warm=warm)

    def _queries(self, request) -> Response:
        with self._lock:
            self.calls += 1
            if self.fail_next > 0:
                self.fail_next -= 1
                raise HTTPError(500, "injected replica failure")
        q = json.loads(request.body)
        if isinstance(q, list):
            # the real engine server's shape contract: a batch body on
            # the single-query route is a 400, /batch answers a list
            if request.path == "/queries.json":
                return Response(
                    400, {"message": "query must be a JSON object"}
                )
            return Response(
                200,
                [
                    {"result": item.get("x", 0) + self.offset}
                    for item in q
                ],
            )
        value = (
            float("nan")
            if self.nan_result
            else q.get("x", 0) + self.offset
        )
        return Response(200, {"result": value})


def gate_cfg(**kw):
    from predictionio_tpu.serving import canary as canary_mod

    defaults = dict(
        shadow_sample=1.0,
        min_shadow=3,
        max_divergence=0.05,
        watch_min_requests=2,
        watch_s=0.3,
        shadow_timeout_s=5.0,
        # fake replicas answer in ~ms, so a single scheduler hiccup on
        # a loaded CI box breaches the production 3x latency factor;
        # rollback tests drive the error path instead
        latency_factor=50.0,
    )
    defaults.update(kw)
    return canary_mod.CanaryConfig(**defaults)


def pump_until(base, record, phases, timeout_s=30.0, on_phase=None):
    """POST queries through the router until the swap record reaches
    one of ``phases``; ``on_phase(phase)`` fires on every transition."""
    seen = set()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        phase = record.get("phase")
        if phase not in seen:
            seen.add(phase)
            if on_phase is not None:
                on_phase(phase)
        if phase in phases:
            return seen
        post(base, "/queries.json", {"x": 7}, timeout=10)
        time.sleep(0.01)
    return seen


class TestFleetGate:
    def _serve(self, router):
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        return http, f"http://127.0.0.1:{http.port}"

    def test_gated_swap_shadow_promotes_then_stabilizes(self):
        """The full fleet promotion: staged replica takes NO live
        traffic while shadowing, the divergence gate promotes, the old
        replica parks as standby through the watch, and a clean window
        retires it."""
        a, b = GateReplica("a"), GateReplica("b")
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(),
            gate_timeout_s=30.0,
            watch_timeout_s=20.0,
        )
        http, base = self._serve(router)
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )
            seen = pump_until(
                base, record, ("done", "failed", "rolled_back")
            )
            assert record["phase"] == "done", record
            assert "shadowing" in seen
            assert record["standby"] == "a"
            assert "a" in record["retired"]
            assert router.replica_states() == {"b": HEALTHY}
            assert router.serving_generation == "g2"
            # the recorded gate proves real shadow comparisons ran
            assert record["gate"]["shadowSamples"] >= 3
            assert record["gate"]["meanDivergence"] <= 0.05
            status, body, _ = post(base, "/queries.json", {"x": 9})
            assert status == 200 and body["result"] == 9
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_gated_swap_rejects_divergent_candidate(self):
        """A candidate whose predictions diverge is refused at the ONE
        fleet gate: the old generation keeps serving untouched."""
        a = GateReplica("a")
        b = GateReplica("b", offset=1000)  # always-diverging model
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(),
            gate_timeout_s=30.0,
        )
        http, base = self._serve(router)
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )
            pump_until(base, record, ("done", "failed", "rolled_back"))
            assert record["phase"] == "failed", record
            assert "fleet gate refused" in record["error"]
            assert wait_for(
                lambda: router.replica_states() == {"a": HEALTHY}
            )
            assert router.serving_generation == ""
            assert counter_value(
                router._registry, "pio_router_swaps_total",
                outcome="failed",
            ) == 1
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_nan_candidate_vetoed_immediately(self):
        a = GateReplica("a")
        b = GateReplica("b")
        b.nan_result = True
        router = make_router(
            a, failover_retries=0, gate_config=gate_cfg(),
            gate_timeout_s=30.0,
        )
        http, base = self._serve(router)
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )
            pump_until(base, record, ("done", "failed", "rolled_back"))
            assert record["phase"] == "failed"
            assert "NaN" in record["error"]
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_post_promotion_regression_rolls_fleet_back(self):
        """The new generation passes the gate, then regresses in
        production: the watch rolls the WHOLE fleet back to the parked
        standby — users end on the last-good generation."""
        a, b = GateReplica("a"), GateReplica("b")
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(watch_s=3.0, watch_min_requests=2),
            gate_timeout_s=30.0,
            watch_timeout_s=30.0,
        )
        http, base = self._serve(router)
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )

            def on_phase(phase):
                if phase == "watching":
                    # the promoted generation starts failing
                    b.fail_next = 10**6

            seen = pump_until(
                base, record, ("done", "failed", "rolled_back"),
                on_phase=on_phase,
            )
            assert record["phase"] == "rolled_back", (record, seen)
            # standby readmitted, rejected generation drained
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            assert "b" not in router.replica_states()
            assert router.serving_generation == ""
            assert wait_for(
                lambda: post(base, "/queries.json", {"x": 3})[0] == 200
            )
            assert counter_value(
                router._registry, "pio_router_swaps_total",
                outcome="rolled_back",
            ) == 1
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()


class TestSwapIdempotency:
    def test_same_token_drives_one_swap(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = make_router(a)
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            first = router.rolling_swap(
                b.url, generation="g2", replica_id="b",
                wait=True, token="gen-2",
            )
            assert first["phase"] == "done"
            # a respawned trainer re-drives the same token: the
            # existing record answers; no second swap, no second gate
            replay = router.rolling_swap(
                b.url, generation="g2", replica_id="b2",
                wait=True, token="gen-2",
            )
            assert replay is first
            assert counter_value(
                router._registry, "pio_router_swaps_total", outcome="ok"
            ) == 1
        finally:
            router.close()
            a.close()
            b.close()

    def test_http_replay_answers_200_with_same_record(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = make_router(a)
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            status, rec1, _ = post(
                base, "/admin/swap",
                {"url": b.url, "generation": "g2", "id": "b",
                 "token": "gen-2"},
            )
            assert status == 202
            status, rec2, _ = post(
                base, "/admin/swap",
                {"url": b.url, "generation": "g2", "token": "gen-2"},
            )
            assert status == 200
            assert rec2["id"] == rec1["id"]
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()


class TestFleetGateTrafficShapes:
    def test_batch_traffic_never_vetoes_the_fleet_gate(self):
        """Batch bodies are not shadow-comparable: mirroring one onto
        the staged replica's single-query route would 400 and score as
        a bogus model exception. Batch traffic must ride through a
        gated swap without feeding the sampler — the gate still
        promotes on the single-query samples."""
        a, b = GateReplica("a"), GateReplica("b")
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(),
            gate_timeout_s=30.0,
            watch_timeout_s=20.0,
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )
            assert wait_for(lambda: record["phase"] == "shadowing")
            for i in range(5):
                status, body, _ = post(
                    base, "/batch/queries.json", [{"x": i}]
                )
                assert status == 200 and body == [{"result": i}]
            pump_until(base, record, ("done", "failed", "rolled_back"))
            assert record["phase"] == "done", record
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_graceful_close_mid_watch_leaves_watch_resumable(self):
        """A clean shutdown mid-regression-watch must be no less safe
        than a kill -9 there: the swap stays in "watching" with the
        rollback standby parked (not retired), so the restart resumes
        the watch instead of inheriting a finalized promotion whose
        safety net was destroyed."""
        a, b = GateReplica("a"), GateReplica("b")
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(
                watch_min_requests=10_000, watch_s=30.0
            ),
            gate_timeout_s=30.0,
            watch_timeout_s=60.0,
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b"
            )
            pump_until(base, record, ("watching",))
            assert record["phase"] == "watching"
            router.close()
            assert wait_for(lambda: router._fleet_gate is None)
            assert record["phase"] == "watching"
            assert record["standby"] == "a"
            assert "a" not in record["retired"]
            with router._lock:
                assert router._replicas["a"].state != RETIRED
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()


class TestGatedSwapExclusivity:
    def test_second_gated_swap_refused_while_first_in_flight(self):
        """The fleet gate is a singleton: while one gated swap is
        non-terminal, a DIFFERENT generation's swap is refused (409 on
        the wire) instead of cross-consuming the live gate's verdict —
        only the same token replays to the in-flight record."""
        a, b, c = GateReplica("a"), GateReplica("b"), GateReplica("c")
        router = make_router(
            a,
            failover_retries=0,
            gate_config=gate_cfg(min_shadow=10_000),
            gate_timeout_s=30.0,
        )
        try:
            assert wait_for(
                lambda: router.replica_states().get("a") == HEALTHY
            )
            record = router.rolling_swap(
                b.url, generation="g2", replica_id="b", token="gen-2"
            )
            assert wait_for(lambda: record["phase"] == "shadowing")
            with pytest.raises(ValueError, match="one fleet gate"):
                router.rolling_swap(
                    c.url, generation="g3", replica_id="c",
                    token="gen-3",
                )
            # the refused candidate never joined the pool, and its
            # token reservation was released with it
            assert "c" not in router.replica_states()
            assert "gen-3" not in router._swap_tokens
            # the same token still replays to the in-flight record
            replay = router.rolling_swap(
                b.url, generation="g2", token="gen-2"
            )
            assert replay is record
        finally:
            router.close()
            a.close()
            b.close()
            c.close()


class TestAutoscalerSignals:
    def test_serving_generation_inferred_without_fleet_swap(self):
        """A fleet that never ran a gated swap has no explicitly
        tracked generation; the signal bundle must carry the INFERRED
        one — the autoscaler substitutes it into the spawn template,
        and "" would launch replicas with the wrong/default model."""
        router = make_router(probe_interval_s=999.0)
        try:
            router.add_replica(
                "http://127.0.0.1:9001", replica_id="a", generation="g1"
            )
            router.add_replica(
                "http://127.0.0.1:9002", replica_id="b", generation="g1"
            )
            assert (
                router.autoscaler_signals()["servingGeneration"] == "g1"
            )
            # mixed pool: no single answer — stays empty, never a guess
            router.add_replica(
                "http://127.0.0.1:9003", replica_id="c", generation="g9"
            )
            assert (
                router.autoscaler_signals()["servingGeneration"] == ""
            )
        finally:
            router.close()

    def test_resumed_roll_never_retires_its_standby(self):
        """The standby is POPPED from the victims when parked, never
        appended to record["retired"]: a roll resumed after a restart
        must still exclude it on the explicit-retire-list path, or the
        rollback standby itself gets retired."""
        router = make_router(probe_interval_s=999.0)
        try:
            record = {
                "id": "s1", "phase": "rolling", "generation": "g2",
                "replica": "staged", "retire": ["a", "b"],
                "retired": [], "standby": "a",
            }
            assert router._swap_victims(record) == ["b"]
        finally:
            router.close()


class TestSwapHistoryBound:
    def test_completed_swaps_garbage_collected_active_kept(self):
        """Terminal swap records are bounded (keep last K) while
        in-flight ones are NEVER evicted — the old fixed-size eviction
        could drop an active swap's record mid-roll."""
        from predictionio_tpu.serving.router import (
            _SWAP_HISTORY_KEEP,
            SWAP_TERMINAL_PHASES,
        )

        router = make_router(probe_interval_s=999.0)
        try:
            active = {"id": "live", "phase": "warming", "token": "tl"}
            router._swaps["live"] = active
            router._swap_tokens["tl"] = "live"
            for i in range(_SWAP_HISTORY_KEEP + 10):
                rec = {"id": f"s{i}", "phase": "done", "token": f"t{i}"}
                router._swaps[f"s{i}"] = rec
                router._swap_tokens[f"t{i}"] = f"s{i}"
            closer = {"id": "closer", "phase": "watching", "token": None}
            router._swaps["closer"] = closer
            router._set_swap_phase(closer, "done")
            terminal = [
                s for s in router._swaps.values()
                if s["phase"] in SWAP_TERMINAL_PHASES
            ]
            assert len(terminal) == _SWAP_HISTORY_KEEP
            assert "live" in router._swaps           # active survived
            assert router._swap_tokens["tl"] == "live"
            # evicted records dropped their token mappings
            assert "t0" not in router._swap_tokens
            assert router._swaps_completed_total == 1
        finally:
            router.close()


class TestStatePersistence:
    def test_replica_set_readopted_on_restart(self, tmp_path):
        path = str(tmp_path / "fleet.json")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(
            "http://127.0.0.1:9001", replica_id="a",
            generation="g1", pid=4242,
        )
        r1.add_replica(
            "http://127.0.0.1:9002", replica_id="b", generation="g1"
        )
        r1.park("b")
        r1.close()
        r2 = make_router(probe_interval_s=999.0, state_path=path)
        try:
            assert set(r2.replica_states()) == {"a", "b"}
            with r2._lock:
                assert r2._replicas["a"].pid == 4242
                assert r2._replicas["a"].generation == "g1"
                # the parked standby stays parked: sticky drains
                # survive the restart too
                assert r2._replicas["b"].admin_draining
                assert r2._replicas["b"].state == DRAINING
            assert "adopted 2 replica" in r2._state_note
        finally:
            r2.close()

    def test_stale_state_discarded_loudly(self, tmp_path):
        path = str(tmp_path / "fleet.json")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica("http://127.0.0.1:9001", replica_id="a")
        r1.close()
        # age the save stamp far past any adoption window
        with open(path) as f:
            doc = json.load(f)
        doc["savedAtUtc"] = "2020-01-01T00:00:00+00:00"
        with open(path, "w") as f:
            json.dump(doc, f)
        r2 = make_router(
            probe_interval_s=999.0, state_path=path,
            state_max_age_s=60.0,
        )
        try:
            assert r2.replica_states() == {}
            assert "discarded" in r2._state_note
            assert "old" in r2._state_note
        finally:
            r2.close()

    def test_torn_state_discarded_loudly(self, tmp_path):
        path = str(tmp_path / "fleet.json")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica("http://127.0.0.1:9001", replica_id="a")
        r1.close()
        with open(path) as f:
            doc = json.load(f)
        doc["payload"]["servingGeneration"] = "tampered"
        with open(path, "w") as f:
            json.dump(doc, f)
        r2 = make_router(probe_interval_s=999.0, state_path=path)
        try:
            assert r2.replica_states() == {}
            assert "checksum" in r2._state_note
        finally:
            r2.close()

    def test_quiet_fleet_restamps_state_from_probe_loop(self, tmp_path):
        """Membership/swap transitions are the only event-driven state
        writers: a fleet that serves steadily for longer than the
        adoption window would age its state file into "stale" and a
        restart would discard a live fleet. The probe loop must
        re-stamp the save periodically."""
        path = str(tmp_path / "fleet.json")
        r = make_router(
            probe_interval_s=0.05, state_path=path,
            state_max_age_s=0.3,  # re-stamp threshold = 0.1s
        )
        try:
            r.add_replica("http://127.0.0.1:9001", replica_id="a")
            with open(path) as f:
                first = json.load(f)["savedAtUtc"]
            # no transitions happen, only probes
            assert wait_for(
                lambda: json.load(open(path))["savedAtUtc"] != first,
                timeout_s=5.0,
            ), "probe loop never refreshed the state stamp"
        finally:
            r.close()

    def test_missing_state_file_is_a_quiet_cold_start(self, tmp_path):
        r = make_router(
            probe_interval_s=999.0,
            state_path=str(tmp_path / "never-written.json"),
        )
        try:
            assert r._state_note == ""
        finally:
            r.close()

    def test_cli_replica_flags_rejoin_adopted_fleet(self, tmp_path):
        """`pio-tpu router --replica ... --state-file ...` restarted
        within the adoption window: the CLI replica ids were already
        adopted from the state file — create_router must skip them,
        not crash the restart on a duplicate registration."""
        from predictionio_tpu.serving.router import create_router

        path = str(tmp_path / "fleet.json")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(
            "http://127.0.0.1:9001", replica_id="r0", generation="g1"
        )
        r1.close()
        router, http = create_router(
            ["http://127.0.0.1:9001#g1"],
            host="127.0.0.1",
            port=0,
            probe_interval_s=999.0,
            state_path=path,
            registry=MetricRegistry(),
        )
        http.start()
        try:
            assert set(router.replica_states()) == {"r0"}
        finally:
            router.close()
            http.shutdown()

    def test_completed_total_survives_restart(self, tmp_path):
        """The lifetime completed-swap counter is persisted with the
        records: after a restart the status route must not report
        completedTotal=0 under completedKept>0 (a monitor diffing the
        counter would see it go backwards)."""
        path = str(tmp_path / "fleet.json")
        a, b = FakeReplica("a"), FakeReplica("b")
        r1 = make_router(a, state_path=path)
        try:
            assert wait_for(
                lambda: r1.replica_states().get("a") == HEALTHY
            )
            done = r1.rolling_swap(
                b.url, generation="g2", replica_id="b", wait=True
            )
            assert done["phase"] == "done"
            assert r1._swaps_completed_total == 1
        finally:
            r1.close()
        r2 = make_router(probe_interval_s=999.0, state_path=path)
        try:
            assert r2._swaps_completed_total == 1
        finally:
            r2.close()
            a.close()
            b.close()

    def test_swap_resumed_from_rolling_after_restart(self, tmp_path):
        """A router killed AFTER the gate passed (phase rolling /
        draining-old) finishes the roll on restart: the fleet converges
        to the new generation."""
        path = str(tmp_path / "fleet.json")
        a = FakeReplica("a")
        b = FakeReplica("b")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(a.url, replica_id="a", generation="g1")
        r1.add_replica(b.url, replica_id="b", generation="g2")
        rec = {
            "id": "s1", "token": "gen-2", "phase": "draining-old",
            "generation": "g2", "fromGeneration": "g1",
            "url": b.url, "replica": "b", "standby": None,
            "gated": False, "retired": [], "retire": "others",
            "warmTimeoutS": 10.0, "gate": None, "error": None,
        }
        r1._swaps["s1"] = rec
        r1._swap_tokens["gen-2"] = "s1"
        r1._persist_state()
        r1.close()  # "kill": the swap thread never ran
        r2 = make_router(state_path=path)
        try:
            assert wait_for(
                lambda: r2._swaps["s1"]["phase"] == "done", timeout_s=15
            ), r2._swaps["s1"]
            assert r2._swaps["s1"]["retired"] == ["a"]
            assert wait_for(
                lambda: r2.replica_states() == {"b": HEALTHY}
            )
        finally:
            r2.close()
            a.close()
            b.close()

    def test_resumed_roll_with_dead_new_generation_rolls_back(
        self, tmp_path
    ):
        """A crash that also took the NEW replica down (same-host
        reboot) must not finish the roll — draining the old generation
        would converge the fleet to zero capacity. The resume waits for
        the promoted generation to re-prove itself; when it never does,
        a gated swap rolls the fleet back to the old generation."""
        path = str(tmp_path / "fleet.json")
        a = FakeReplica("a")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(a.url, replica_id="a", generation="g1")
        # the new-generation replica: registered, but nothing listens
        r1.add_replica(
            "http://127.0.0.1:9", replica_id="b", generation="g2"
        )
        rec = {
            "id": "s1", "token": "gen-2", "phase": "rolling",
            "generation": "g2", "fromGeneration": "g1",
            "url": "http://127.0.0.1:9", "replica": "b",
            "standby": None, "gated": True, "retired": [],
            "retire": "others", "warmTimeoutS": 1.0, "gate": None,
            "error": None,
        }
        r1._swaps["s1"] = rec
        r1._swap_tokens["gen-2"] = "s1"
        r1._persist_state()
        r1.close()
        r2 = make_router(state_path=path)
        try:
            assert wait_for(
                lambda: r2._swaps["s1"]["phase"] == "rolled_back",
                timeout_s=15,
            ), r2._swaps["s1"]
            assert "no 'g2' replica became healthy" in (
                r2._swaps["s1"]["error"]
            )
            # the old generation was never drained and keeps serving
            assert "a" not in r2._swaps["s1"]["retired"]
            assert wait_for(
                lambda: r2.replica_states() == {"a": HEALTHY}
            )
            assert r2.serving_generation == "g1"
        finally:
            r2.close()
            a.close()

    def test_resumed_ungated_drain_with_dead_new_generation_fails_safe(
        self, tmp_path
    ):
        """Same crash shape for a plain (ungated) swap: there is no
        rollback machinery, so the resume fails the swap — the old
        generation keeps serving untouched."""
        path = str(tmp_path / "fleet.json")
        a = FakeReplica("a")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(a.url, replica_id="a", generation="g1")
        r1.add_replica(
            "http://127.0.0.1:9", replica_id="b", generation="g2"
        )
        rec = {
            "id": "s1", "token": "gen-2", "phase": "draining-old",
            "generation": "g2", "fromGeneration": "g1",
            "url": "http://127.0.0.1:9", "replica": "b",
            "standby": None, "gated": False, "retired": [],
            "retire": "others", "warmTimeoutS": 1.0, "gate": None,
            "error": None,
        }
        r1._swaps["s1"] = rec
        r1._swap_tokens["gen-2"] = "s1"
        r1._persist_state()
        r1.close()
        r2 = make_router(state_path=path)
        try:
            assert wait_for(
                lambda: r2._swaps["s1"]["phase"] == "failed",
                timeout_s=15,
            ), r2._swaps["s1"]
            assert wait_for(
                lambda: r2.replica_states() == {"a": HEALTHY}
            )
        finally:
            r2.close()
            a.close()

    def test_persisted_swap_snapshot_isolated_from_live_mutation(
        self, tmp_path
    ):
        """The persisted payload must be a point-in-time deep copy: a
        shallow snapshot would share nested objects (retired list, gate
        dict) with live swap threads, whose later mutations could tear
        the file against its own checksum."""
        path = str(tmp_path / "fleet.json")
        router = make_router(probe_interval_s=999.0, state_path=path)
        try:
            rec = {
                "id": "s1", "token": None, "phase": "rolling",
                "generation": "g2", "retired": [], "gate": None,
            }
            router._swaps["s1"] = rec
            router._persist_state()
            # live mutation AFTER the snapshot was written
            rec["retired"].append("a")
            rec["gate"] = {"shadowSamples": 3}
            from predictionio_tpu.serving.router import RouterStateStore

            payload, reason = RouterStateStore(path).load(
                max_age_s=3600.0
            )
            assert reason == "" and payload is not None
            (saved,) = payload["swaps"]
            assert saved["retired"] == []
            assert saved["gate"] is None
        finally:
            router.close()

    def test_swap_aborted_from_shadowing_after_restart(self, tmp_path):
        """A router killed BEFORE the gate passed aborts to the old
        generation: the unproven candidate is retired, the fleet keeps
        serving what it served."""
        path = str(tmp_path / "fleet.json")
        a = FakeReplica("a")
        b = FakeReplica("b")
        r1 = make_router(probe_interval_s=999.0, state_path=path)
        r1.add_replica(a.url, replica_id="a", generation="g1")
        staged = r1.add_replica(
            b.url, replica_id="b", generation="g2", staged=True
        )
        assert staged.staged
        rec = {
            "id": "s1", "token": "gen-2", "phase": "shadowing",
            "generation": "g2", "fromGeneration": "g1",
            "url": b.url, "replica": "b", "standby": None,
            "gated": True, "retired": [], "retire": "others",
            "warmTimeoutS": 10.0, "gate": None, "error": None,
        }
        r1._swaps["s1"] = rec
        r1._swap_tokens["gen-2"] = "s1"
        r1._persist_state()
        r1.close()
        r2 = make_router(state_path=path)
        try:
            assert wait_for(
                lambda: r2._swaps["s1"]["phase"] == "failed",
                timeout_s=15,
            ), r2._swaps["s1"]
            assert "aborted" in r2._swaps["s1"]["error"]
            assert wait_for(
                lambda: r2.replica_states() == {"a": HEALTHY}
            )
            # the idempotency token still answers the aborted record —
            # a resumed trainer learns the outcome instead of silently
            # re-promoting
            replay = r2.rolling_swap(
                b.url, generation="g2", token="gen-2"
            )
            assert replay["id"] == "s1"
        finally:
            r2.close()
            a.close()
            b.close()

    def test_staged_replica_takes_no_selection_traffic(self):
        a = GateReplica("a")
        b = GateReplica("b")
        router = make_router(a, failover_retries=0)
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            router.add_replica(b.url, replica_id="b", staged=True)
            assert wait_for(
                lambda: set(router.replica_states().values())
                == {HEALTHY}
            )
            for i in range(10):
                status, _, _ = post(base, "/queries.json", {"x": i})
                assert status == 200
            assert b.calls == 0  # healthy but staged: zero live traffic
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()
