"""serving/stats.py coverage (ISSUE 1 satellite): hour-bucket rollover,
multi-app isolation, and concurrent update() — the lock finally gets
exercised. Registry mirroring lives in ``EventServer._count`` (single
site) and is covered end-to-end in test_obs.py."""

import datetime as dt
import threading

from predictionio_tpu.data.event import Event
from predictionio_tpu.serving import stats as stats_mod
from predictionio_tpu.serving.stats import Stats


def _event(name="view", entity_type="user"):
    return Event(event=name, entity_type=entity_type, entity_id="e1")


class TestHourBuckets:
    def test_rollover_creates_a_new_bucket(self, monkeypatch):
        t = dt.datetime(2026, 8, 2, 10, 59, tzinfo=dt.timezone.utc)
        monkeypatch.setattr(stats_mod, "_now", lambda: t)
        s = Stats()
        s.update(1, 201, _event())
        # clock crosses the hour boundary
        t2 = t + dt.timedelta(minutes=2)
        monkeypatch.setattr(stats_mod, "_now", lambda: t2)
        s.update(1, 201, _event())
        buckets = {bucket for bucket, _aid in s._status}
        assert buckets == {
            "2026-08-02T10:00:00Z",
            "2026-08-02T11:00:00Z",
        }
        # snapshot aggregates across buckets
        assert s.snapshot(1)["statusCount"] == {"201": 2}

    def test_bucket_is_utc_even_for_offset_times(self, monkeypatch):
        tz = dt.timezone(dt.timedelta(hours=5, minutes=30))
        t = dt.datetime(2026, 8, 2, 1, 15, tzinfo=tz)  # 19:45Z prev day
        monkeypatch.setattr(stats_mod, "_now", lambda: t)
        s = Stats()
        s.update(1, 201)
        (bucket, _aid), = s._status
        assert bucket == "2026-08-01T19:00:00Z"


class TestMultiAppIsolation:
    def test_snapshots_do_not_mix_apps(self):
        s = Stats()
        s.update(1, 201, _event("view"))
        s.update(1, 400)
        s.update(2, 201, _event("buy", entity_type="order"))
        snap1 = s.snapshot(1)
        snap2 = s.snapshot(2)
        assert snap1["statusCount"] == {"201": 1, "400": 1}
        assert snap1["eventCount"] == {"view": 1}
        assert snap2["statusCount"] == {"201": 1}
        assert snap2["eventCount"] == {"buy": 1}
        assert snap2["entityTypeCount"] == {"order": 1}

    def test_unknown_app_snapshot_is_empty(self):
        s = Stats()
        s.update(1, 201)
        assert s.snapshot(99)["statusCount"] == {}


class TestConcurrency:
    def test_concurrent_updates_lose_nothing(self):
        s = Stats()
        n_threads, per_thread = 8, 500
        barrier = threading.Barrier(n_threads)

        def work(app_id):
            barrier.wait()
            for _ in range(per_thread):
                s.update(app_id, 201, _event())

        threads = [
            threading.Thread(target=work, args=(i % 2,))
            for i in range(n_threads)
        ]
        [t.start() for t in threads]
        [t.join() for t in threads]
        total = (
            s.snapshot(0)["statusCount"]["201"]
            + s.snapshot(1)["statusCount"]["201"]
        )
        assert total == n_threads * per_thread
        assert s.snapshot(0)["eventCount"]["view"] == 2000

    def test_concurrent_update_and_snapshot(self):
        """snapshot() while updates are in flight must neither crash
        nor observe torn counters (RuntimeError on dict mutation)."""
        s = Stats()
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                try:
                    snap = s.snapshot(1)
                    assert snap["statusCount"].get("201", 0) >= 0
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        t = threading.Thread(target=reader)
        t.start()
        for _ in range(2000):
            s.update(1, 201)
        stop.set()
        t.join()
        assert errors == []
        assert s.snapshot(1)["statusCount"] == {"201": 2000}
