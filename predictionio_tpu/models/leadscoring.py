"""Lead-scoring template — conversion probability by logistic regression.

Gallery parity: PredictionIO's template gallery shipped a Lead Scoring
engine (session features → purchase probability, MLlib tree models; the
reference repo links the gallery rather than bundling it — the nearest
in-tree pattern is ``examples/scala-parallel-classification``, whose
DASE layout this follows). Users carry ``$set`` numeric attributes plus
a boolean conversion label; queries ``{"features": [...]}`` answer
``{"score": p, "converted": p >= threshold}``.

TPU-first redesign — and the framework's gradient-descent exemplar:
where every other bundled algorithm is closed-form (ALS normal
equations, NB sufficient statistics, co-occurrence counts), this one
trains by the standard JAX loop — an optax optimizer stepped inside
``lax.scan``, the whole ``steps``-iteration descent compiled ONCE and
dispatched as a single device program (no per-step Python, no
data-dependent shapes). Features are standardized at the Preparator
boundary with moments carried into the model so serving normalizes
identically.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    register_engine,
)
from predictionio_tpu.core.controller import SanityCheck
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.parallel.mesh import ComputeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class LeadDataSourceParams(Params):
    app_name: str = "MyApp"
    entity_type: str = "user"
    attributes: tuple[str, ...] = ("sessions", "pages", "minutes")
    label_property: str = "converted"
    eval_k: int = 0  # >0 enables k-fold read_eval


@dataclasses.dataclass
class LeadTrainingData(SanityCheck):
    x: np.ndarray  # float32 [n, d]
    y: np.ndarray  # float32 [n] in {0, 1}

    def sanity_check(self) -> None:
        if len(self.x) == 0:
            raise ValueError("no labeled leads found — seed data first")
        if not np.isfinite(self.x).all():
            # one NaN attribute would poison the standardization moments
            # and every trained weight — fail at read, not at serve
            raise ValueError("lead features contain NaN/inf values")
        if len(np.unique(self.y)) < 2:
            raise ValueError(
                "need both converted and unconverted leads to fit"
            )


class LeadDataSource(DataSource[LeadTrainingData, dict, dict, list]):
    params_class = LeadDataSourceParams

    def _read(self) -> LeadTrainingData:
        p = self.params
        props = EventStore().aggregate_properties(
            p.app_name, p.entity_type,
            required=[*p.attributes, p.label_property],
        )
        rows, labels = [], []
        for entity_id, pm in props.items():
            rows.append([float(pm[a]) for a in p.attributes])
            raw = pm[p.label_property]
            # bool/0/1 only: bool("false") is True, so a CSV-derived
            # string label would silently invert the training signal
            if not isinstance(raw, bool) and raw not in (0, 1):
                raise ValueError(
                    f"label {p.label_property!r} of entity "
                    f"{entity_id!r} must be a boolean, got {raw!r}"
                )
            labels.append(1.0 if raw else 0.0)
        return LeadTrainingData(
            x=np.asarray(rows, np.float32).reshape(
                len(rows), len(p.attributes)
            ),
            y=np.asarray(labels, np.float32),
        )

    def read_training(self, ctx: ComputeContext) -> LeadTrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        from predictionio_tpu.core.evaluation import kfold_indices

        full = self._read()
        folds = []
        for fold, train_idx, test_idx in kfold_indices(
            len(full.x), self.params.eval_k
        ):
            td = LeadTrainingData(
                x=full.x[train_idx], y=full.y[train_idx]
            )
            qa = [
                (
                    {"features": full.x[i].tolist()},
                    bool(full.y[i]),
                )
                for i in test_idx
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


@dataclasses.dataclass
class LeadPrepared:
    x: object          # [n_pad, d] standardized, data-sharded
    y: object          # float32 [n_pad], data-sharded
    mask: object       # float32 [n_pad]
    mean: np.ndarray   # [d] training-fold feature means
    std: np.ndarray    # [d] training-fold feature stds (>= eps)


class LeadPreparator(Preparator[LeadTrainingData, LeadPrepared]):
    """Standardize at the fixed-shape boundary; the moments ride along
    so serving normalizes queries identically."""

    def prepare(
        self, ctx: ComputeContext, td: LeadTrainingData
    ) -> LeadPrepared:
        mean = td.x.mean(axis=0)
        std = np.maximum(td.x.std(axis=0), 1e-6)
        x = (td.x - mean) / std
        return LeadPrepared(
            x=ctx.shard_rows(x.astype(np.float32)),
            y=ctx.shard_rows(td.y),
            mask=ctx.shard_rows(np.ones(len(td.x), np.float32)),
            mean=mean.astype(np.float32),
            std=std.astype(np.float32),
        )


@dataclasses.dataclass(frozen=True)
class LeadScoringParams(Params):
    learning_rate: float = 0.1
    steps: int = 500
    l2: float = 1e-3
    #: classification cut for the boolean "converted" answer
    threshold: float = 0.5
    seed: int = 7


@dataclasses.dataclass
class LeadModel:
    w: np.ndarray      # [d]
    b: float
    mean: np.ndarray   # [d]
    std: np.ndarray    # [d]
    threshold: float

    def score(self, features: np.ndarray) -> np.ndarray:
        z = ((features - self.mean) / self.std) @ self.w + self.b
        return 1.0 / (1.0 + np.exp(-z))


class LeadScoringAlgorithm(
    Algorithm[LeadPrepared, LeadModel, dict, dict]
):
    params_class = LeadScoringParams

    def train(self, ctx: ComputeContext, data: LeadPrepared) -> LeadModel:
        p = self.params
        d = data.mean.shape[0]
        opt = optax.adam(p.learning_rate)

        def loss_fn(params, x, y, mask):
            logits = x @ params["w"] + params["b"]
            bce = optax.sigmoid_binary_cross_entropy(logits, y)
            data_term = (bce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
            return data_term + p.l2 * (params["w"] ** 2).sum()

        @jax.jit
        def fit(x, y, mask):
            """The whole descent as ONE compiled program: optax steps
            unrolled by lax.scan — no per-step Python dispatch."""
            params = {
                "w": jnp.zeros(d, jnp.float32),
                "b": jnp.float32(0.0),
            }
            state = opt.init(params)
            grad = jax.grad(loss_fn)

            def step(carry, _):
                params, state = carry
                g = grad(params, x, y, mask)
                updates, state = opt.update(g, state, params)
                return (optax.apply_updates(params, updates), state), ()

            (params, _state), _ = jax.lax.scan(
                step, (params, state), None, length=p.steps
            )
            return params

        params = fit(data.x, data.y, data.mask)
        logger.info(
            "lead-scoring logistic regression: d=%d, %d steps", d, p.steps
        )
        return LeadModel(
            w=np.asarray(params["w"]),
            b=float(params["b"]),
            mean=data.mean,
            std=data.std,
            threshold=p.threshold,
        )

    def predict(self, model: LeadModel, query: dict) -> dict:
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: LeadModel, queries) -> list[dict]:
        if not queries:
            return []
        x = np.asarray(
            [q["features"] for q in queries], np.float32
        )
        scores = model.score(x)
        # the DEPLOY-TIME params cut the boolean: threshold is a pure
        # serving knob, so editing engine.json + redeploy must take
        # effect without a retrain (model.threshold records what the
        # training run used, for provenance)
        threshold = self.params.threshold
        return [
            {
                "score": float(s),
                "converted": bool(s >= threshold),
            }
            for s in scores
        ]

    def warmup_query(self) -> dict | None:
        return None  # feature width is data-dependent; serve cold


def leadscoring_engine() -> Engine:
    return Engine(
        LeadDataSource,
        LeadPreparator,
        {"logreg": LeadScoringAlgorithm},
        FirstServing,
    )


register_engine("leadscoring", leadscoring_engine)
