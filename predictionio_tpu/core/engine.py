"""Engine — the DASE assembly + train/eval pipelines.

Capability parity with the reference ``controller/Engine.scala``:

* class maps name→controller class for the four components
  (Engine.scala:80-130);
* ``train`` = read → sanity-check → prepare → sanity-check → per-algorithm
  train → sanity-check, honoring stop-after-read / stop-after-prepare
  interrupts and skip-sanity-check (object Engine.train:622-709);
* ``eval`` = per-fold multi-algorithm batch predict + serving join
  (object Engine.eval:727-817) — the reference's EX/AX/QX RDD index
  gymnastics reduce to plain loops over host query lists, with the bulk
  compute inside each algorithm's (jitted) ``batch_predict``;
* ``prepare_deploy`` = load persisted / retrain Unit-model algorithms
  (Engine.scala:196-254);
* engine.json variant → :class:`EngineParams`
  (``jValueToEngineParams``, Engine.scala:354-417).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Mapping, Sequence

from predictionio_tpu.core.controller import (
    Algorithm,
    DataSource,
    EmptyParams,
    Params,
    ParamsError,
    PersistenceMode,
    Preparator,
    SanityCheck,
    Serving,
    params_from_json,
)
from predictionio_tpu.parallel.mesh import ComputeContext

logger = logging.getLogger(__name__)


class StopAfterReadInterruption(Exception):
    """Reference WorkflowUtils.scala:379-383."""


class StopAfterPrepareInterruption(Exception):
    pass


@dataclasses.dataclass
class WorkflowParams:
    """Reference workflow/WorkflowParams.scala:29-42."""

    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False


@dataclasses.dataclass
class EngineParams:
    """Named (component-name, params) selection (reference
    controller/EngineParams.scala:32-147)."""

    data_source: tuple[str, Params] = ("", EmptyParams())
    preparator: tuple[str, Params] = ("", EmptyParams())
    algorithms: Sequence[tuple[str, Params]] = (("", EmptyParams()),)
    serving: tuple[str, Params] = ("", EmptyParams())


def _sanity(obj: Any, stage: str, skip: bool) -> None:
    if skip:
        return
    if isinstance(obj, SanityCheck):
        logger.debug("sanity_check %s (%s)", stage, type(obj).__name__)
        obj.sanity_check()


class Engine:
    """The DASE assembly.

    ``*_classes`` are name→class maps; a map with a single entry accepts
    the empty name "" (the reference's single-class constructor sugar,
    Engine.scala:143-172).
    """

    def __init__(
        self,
        data_source_classes: (
            Mapping[str, type[DataSource]] | type[DataSource]
        ),
        preparator_classes: Mapping[str, type[Preparator]] | type[Preparator],
        algorithm_classes: Mapping[str, type[Algorithm]] | type[Algorithm],
        serving_classes: Mapping[str, type[Serving]] | type[Serving],
    ):
        def _as_map(x, base):
            if isinstance(x, Mapping):
                return dict(x)
            if isinstance(x, type) and issubclass(x, base):
                return {"": x}
            raise TypeError(f"expected class or name→class map, got {x!r}")

        self.data_source_classes = _as_map(data_source_classes, DataSource)
        self.preparator_classes = _as_map(preparator_classes, Preparator)
        self.algorithm_classes = _as_map(algorithm_classes, Algorithm)
        self.serving_classes = _as_map(serving_classes, Serving)

    # -- component instantiation (the Doer equivalent) --------------------
    def _one(self, classes: Mapping[str, type], name: str, kind: str):
        if name in classes:
            return classes[name]
        if name == "" and len(classes) == 1:
            return next(iter(classes.values()))
        raise ParamsError(
            f"unknown {kind} {name!r}; available: {sorted(classes)}"
        )

    def make_data_source(self, params: EngineParams) -> DataSource:
        name, p = params.data_source
        return self._one(self.data_source_classes, name, "data source")(p)

    def make_preparator(self, params: EngineParams) -> Preparator:
        name, p = params.preparator
        return self._one(self.preparator_classes, name, "preparator")(p)

    def make_algorithms(self, params: EngineParams) -> list[Algorithm]:
        return [
            self._one(self.algorithm_classes, name, "algorithm")(p)
            for name, p in params.algorithms
        ]

    def make_serving(self, params: EngineParams) -> Serving:
        name, p = params.serving
        return self._one(self.serving_classes, name, "serving")(p)

    # -- training pipeline (object Engine.train:622-709) ------------------
    def train(
        self,
        ctx: ComputeContext,
        params: EngineParams,
        workflow: WorkflowParams | None = None,
        algorithms: list[Algorithm] | None = None,
    ) -> list[Any]:
        """``algorithms`` may be pre-built so callers (run_train) can keep
        the *same* instances for MANUAL-persistence save_model calls."""
        workflow = workflow or WorkflowParams()
        # resolve every component up front (fail fast on bad names/params)
        data_source = self.make_data_source(params)
        preparator = self.make_preparator(params)
        if algorithms is None:
            algorithms = self.make_algorithms(params)
        td = data_source.read_training(ctx)
        _sanity(td, "training data", workflow.skip_sanity_check)
        if workflow.stop_after_read:
            raise StopAfterReadInterruption()

        pd = preparator.prepare(ctx, td)
        _sanity(pd, "prepared data", workflow.skip_sanity_check)
        if workflow.stop_after_prepare:
            raise StopAfterPrepareInterruption()

        models: list[Any] = []
        for i, algo in enumerate(algorithms):
            logger.info(
                "training algorithm %d/%d (%s)",
                i + 1,
                len(params.algorithms),
                type(algo).__name__,
            )
            model = algo.train(ctx, pd)
            _sanity(model, f"model[{i}]", workflow.skip_sanity_check)
            models.append(model)
        return models

    # -- evaluation pipeline (object Engine.eval:727-817) -----------------
    def eval(
        self,
        ctx: ComputeContext,
        params: EngineParams,
        workflow: WorkflowParams | None = None,
    ) -> list[tuple[Any, list[tuple[Any, Any, Any]]]]:
        """Per evaluation fold: (evalInfo, [(query, prediction, actual)])."""
        workflow = workflow or WorkflowParams()
        data_source = self.make_data_source(params)
        preparator = self.make_preparator(params)
        algorithms = self.make_algorithms(params)
        serving = self.make_serving(params)

        results = []
        for fold, (td, eval_info, qa) in enumerate(
            data_source.read_eval(ctx)
        ):
            _sanity(td, f"fold[{fold}] training data", workflow.skip_sanity_check)
            pd = preparator.prepare(ctx, td)
            _sanity(pd, f"fold[{fold}] prepared data", workflow.skip_sanity_check)
            queries = [serving.supplement(q) for q, _ in qa]
            actuals = [a for _, a in qa]
            # per-algorithm bulk predict (the reference's AX/QX join)
            per_algo: list[list[Any]] = []
            for algo in algorithms:
                model = algo.train(ctx, pd)
                per_algo.append(list(algo.batch_predict(model, queries)))
            qpa = [
                (q, serving.serve(q, [preds[i] for preds in per_algo]), a)
                for i, (q, a) in enumerate(zip(queries, actuals))
            ]
            results.append((eval_info, qpa))
        return results

    # -- deploy-time model recovery (Engine.prepareDeploy:196-254) --------
    def prepare_deploy(
        self,
        ctx: ComputeContext,
        params: EngineParams,
        instance_id: str,
        stored_models: Sequence[Any],
    ) -> tuple[list[Algorithm], list[Any], Serving]:
        algorithms = self.make_algorithms(params)
        if len(stored_models) != len(algorithms):
            raise RuntimeError(
                f"engine params declare {len(algorithms)} algorithm(s) but "
                f"instance {instance_id} persisted {len(stored_models)} "
                f"model(s); retrain with the current params"
            )
        models: list[Any] = []
        for i, (algo, stored) in enumerate(zip(algorithms, stored_models)):
            mode = algo.persistence_mode
            if mode == PersistenceMode.AUTO:
                models.append(stored)
            elif mode == PersistenceMode.MANUAL:
                models.append(algo.load_model(instance_id, ctx))
            else:  # RETRAIN: re-run the pipeline for this algorithm
                logger.info(
                    "algorithm %d (%s) uses RETRAIN persistence; re-training",
                    i,
                    type(algo).__name__,
                )
                data_source = self.make_data_source(params)
                td = data_source.read_training(ctx)
                pd = self.make_preparator(params).prepare(ctx, td)
                models.append(algo.train(ctx, pd))
        # stage every model onto the device(s) once — serving must never
        # pay a per-request host→device model transfer
        models = [
            algo.stage_model(ctx, model)
            for algo, model in zip(algorithms, models)
        ]
        return algorithms, models, self.make_serving(params)

    # -- engine.json variant → EngineParams (Engine.scala:354-417) --------
    def params_from_variant(self, variant: Mapping[str, Any]) -> EngineParams:
        def _component(key: str, classes: Mapping[str, type]) -> tuple[str, Params]:
            node = variant.get(key) or {}
            name = node.get("name", "")
            cls = self._one(classes, name, key)
            return (name, params_from_json(
                getattr(cls, "params_class", EmptyParams), node.get("params")
            ))

        algo_nodes = variant.get("algorithms")
        if algo_nodes:
            algorithms = []
            for node in algo_nodes:
                name = node.get("name", "")
                cls = self._one(self.algorithm_classes, name, "algorithm")
                algorithms.append(
                    (
                        name,
                        params_from_json(
                            getattr(cls, "params_class", EmptyParams),
                            node.get("params"),
                        ),
                    )
                )
        else:
            algorithms = [("", EmptyParams())]
        return EngineParams(
            data_source=_component("datasource", self.data_source_classes),
            preparator=_component("preparator", self.preparator_classes),
            algorithms=algorithms,
            serving=_component("serving", self.serving_classes),
        )


#: An engine factory is any zero-arg callable returning an Engine
#: (reference EngineFactory.apply, SURVEY.md §1 L7).
EngineFactory = Callable[[], Engine]
