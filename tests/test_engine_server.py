"""Engine Server tests over a real socket: queries.json hot path,
micro-batching, reload, feedback loop (reference ServerActor behavior)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from fake_engine import (
    FakeAlgorithm,
    FakeDataSource,
    FakeParams,
    FakePreparator,
    FakeServing,
)
from predictionio_tpu.core import Engine, EngineParams
from predictionio_tpu.core.workflow import run_train
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.serving.batching import MicroBatcher
from predictionio_tpu.serving.engine_server import EngineServer


@pytest.fixture(scope="module")
def ctx():
    return ComputeContext.create(batch="srv-test")


def _call(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class DictQueryAlgorithm(FakeAlgorithm):
    """Fake algorithm answering dict queries (the server speaks JSON)."""

    def predict(self, model, query):
        return {"result": model.algo_id * 10 + int(query.get("x", 0))}

    def batch_predict(self, model, queries):
        return [self.predict(model, q) for q in queries]


class DictServing(FakeServing):
    def serve(self, query, predictions):
        return predictions[0]


def _engine():
    return Engine(
        FakeDataSource, FakePreparator, DictQueryAlgorithm, DictServing
    )


def _params():
    return EngineParams(
        data_source=("", FakeParams(id=1)),
        preparator=("", FakeParams(id=2)),
        algorithms=[("", FakeParams(id=3))],
        serving=("", FakeParams()),
    )


@pytest.fixture()
def server(ctx, memory_storage):
    run_train(
        _engine(), _params(), engine_id="srv", ctx=ctx,
        storage=memory_storage,
    )
    es = EngineServer(
        _engine(),
        _params(),
        engine_id="srv",
        storage=memory_storage,
        ctx=ctx,
        feedback=True,
        feedback_app_id=1,
    )
    memory_storage.get_events().init(1)
    http = es.serve(host="127.0.0.1", port=0)
    http.start()
    yield f"http://127.0.0.1:{http.port}", es, memory_storage
    http.shutdown()
    es.close()


class TestEngineServer:
    def test_status_page(self, server):
        base, _, _ = server
        status, body = _call(f"{base}/")
        assert status == 200
        assert body["engineId"] == "srv"
        assert body["requestCount"] == 0

    def test_query_hot_path(self, server):
        base, _, _ = server
        status, body = _call(
            f"{base}/queries.json", "POST", {"x": 7}
        )
        assert status == 200
        assert body["result"] == 37  # algo_id 3 → 30 + x
        _, info = _call(f"{base}/")
        assert info["requestCount"] == 1
        assert info["lastServingSec"] > 0

    def test_feedback_event_recorded_and_prid_injected(self, server):
        base, _, storage = server
        _, body = _call(f"{base}/queries.json", "POST", {"x": 1})
        assert "prId" in body
        events = list(
            storage.get_events().find(1, entity_type="pio_pr")
        )
        assert len(events) == 1
        assert events[0].event == "predict"
        assert events[0].properties["query"] == {"x": 1}

    def test_concurrent_queries_batched(self, server):
        base, es, _ = server
        results = [None] * 32

        def call(i):
            _, body = _call(f"{base}/queries.json", "POST", {"x": i})
            results[i] = body["result"]

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(32)
        ]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert results == [30 + i for i in range(32)]

    def test_reload_picks_latest(self, server, ctx, memory_storage):
        base, es, _ = server
        old_instance = es._instance.id
        run_train(
            _engine(), _params(), engine_id="srv", ctx=ctx,
            storage=memory_storage,
        )
        status, body = _call(f"{base}/reload", "POST")
        assert status == 200
        assert body["engineInstanceId"] != old_instance
        status, body = _call(f"{base}/queries.json", "POST", {"x": 2})
        assert body["result"] == 32

    def test_malformed_query(self, server):
        base, _, _ = server
        status, _ = _call(f"{base}/queries.json", "POST", [1, 2, 3])
        assert status == 400

    def test_html_status_page_content_negotiated(self, server):
        """GET / with Accept: text/html renders the status page
        (reference twirl index.scala.html); JSON stays the default."""
        base, _, _ = server
        req = urllib.request.Request(
            f"{base}/", headers={"Accept": "text/html"}
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["Content-Type"] == "text/html"
            page = resp.read().decode()
        assert "<h1>Engine Server</h1>" in page
        assert "srv" in page
        assert "Engine Information" in page
        assert "Request Count" in page
        # default (no Accept preference) remains JSON
        status, body = _call(f"{base}/")
        assert status == 200 and body["status"] == "alive"


class TestBatchQueries:
    def test_batch_roundtrip_per_query_results(self, server):
        base, _, _ = server
        status, body = _call(
            f"{base}/batch/queries.json", "POST",
            [{"x": 1}, {"x": 2}, {"x": 3}],
        )
        assert status == 200
        assert [r["status"] for r in body] == [200, 200, 200]
        assert [r["prediction"]["result"] for r in body] == [31, 32, 33]

    def test_batch_matches_single_query_path(self, server):
        base, _, _ = server
        _, single = _call(f"{base}/queries.json", "POST", {"x": 9})
        _, batch = _call(
            f"{base}/batch/queries.json", "POST", [{"x": 9}]
        )
        # feedback injects a fresh prId per call; everything else equal
        single.pop("prId", None)
        got = batch[0]["prediction"]
        got.pop("prId", None)
        assert got == single

    def test_bad_slot_keeps_per_query_status(self, server):
        base, _, _ = server
        status, body = _call(
            f"{base}/batch/queries.json", "POST",
            [{"x": 1}, "not-a-query", {"x": 2}],
        )
        assert status == 200
        assert [r["status"] for r in body] == [200, 400, 200]
        assert "JSON object" in body[1]["message"]

    def test_non_array_rejected(self, server):
        base, _, _ = server
        status, body = _call(
            f"{base}/batch/queries.json", "POST", {"x": 1}
        )
        assert status == 400
        assert "array" in body["message"]

    def test_batch_limit(self, server):
        base, _, _ = server
        status, body = _call(
            f"{base}/batch/queries.json", "POST",
            [{"x": i} for i in range(101)],
        )
        assert status == 400
        assert "100" in body["message"]

    def test_batch_counts_toward_stats(self, server):
        base, _, _ = server
        _, before = _call(f"{base}/")
        _call(
            f"{base}/batch/queries.json", "POST",
            [{"x": i} for i in range(5)],
        )
        _, after = _call(f"{base}/")
        assert after["requestCount"] == before["requestCount"] + 5

    def test_empty_batch_is_empty_list(self, server):
        """[] on a fresh server must not divide by a zero request
        count in the stats update."""
        base, _, _ = server
        status, body = _call(f"{base}/batch/queries.json", "POST", [])
        assert status == 200 and body == []

    def test_supplement_error_stays_per_slot(self, server, monkeypatch):
        """A serving.supplement that rejects one query must produce a
        500 in THAT slot only — not reclassify the batch as a reload or
        abandon the other slots."""
        base, es, _ = server
        original = es._serving.supplement

        def picky(query):
            if query.get("x") == 13:
                raise ValueError("unlucky query")
            return original(query)

        monkeypatch.setattr(es._serving, "supplement", picky)
        status, body = _call(
            f"{base}/batch/queries.json", "POST",
            [{"x": 1}, {"x": 13}, {"x": 2}],
        )
        assert status == 200
        assert [r["status"] for r in body] == [200, 500, 200]
        assert "unlucky" in body[1]["message"]

    def test_batch_feedback_events_recorded(self, server):
        base, _, storage = server
        before = len(list(
            storage.get_events().find(1, entity_type="pio_pr")
        ))
        _, body = _call(
            f"{base}/batch/queries.json", "POST", [{"x": 1}, {"x": 2}]
        )
        assert all("prId" in r["prediction"] for r in body)
        after = len(list(
            storage.get_events().find(1, entity_type="pio_pr")
        ))
        assert after == before + 2


class TestBindAndUndeploy:
    def test_undeploy_before_deploy_stops_old_server(
        self, ctx, memory_storage
    ):
        """Second deploy on the same port posts /stop to the first and
        takes the port over (reference MasterActor StartServer →
        undeploy, CreateServer.scala:280-378)."""
        import time as _time

        run_train(
            _engine(), _params(), engine_id="srv", ctx=ctx,
            storage=memory_storage,
        )
        first = EngineServer(
            _engine(), _params(), engine_id="srv",
            storage=memory_storage, ctx=ctx, warmup=False,
        )
        http1 = first.serve(host="127.0.0.1", port=0)
        http1.start()
        port = http1.port
        second = EngineServer(
            _engine(), _params(), engine_id="srv",
            storage=memory_storage, ctx=ctx, warmup=False,
        )
        # bind_retries gives the old server time to release the socket
        http2 = second.serve(host="127.0.0.1", port=port)
        http2.start()
        try:
            status, body = _call(f"http://127.0.0.1:{port}/")
            assert status == 200 and body["status"] == "alive"
        finally:
            http2.shutdown()
            second.close()
            first.close()
        _time.sleep(0.1)

    def test_bind_retry_then_give_up(self, ctx, memory_storage, monkeypatch):
        """A port held by a non-engine process: undeploy fails, bind
        retries x3, then the original error surfaces."""
        import socket as _socket

        run_train(
            _engine(), _params(), engine_id="srv", ctx=ctx,
            storage=memory_storage,
        )
        blocker = _socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        sleeps = []
        monkeypatch.setattr(
            "predictionio_tpu.serving.engine_server.time.sleep",
            sleeps.append,
        )
        es = EngineServer(
            _engine(), _params(), engine_id="srv",
            storage=memory_storage, ctx=ctx, warmup=False,
        )
        try:
            with pytest.raises(OSError):
                es.serve(
                    host="127.0.0.1", port=port, bind_retries=3,
                    undeploy_first=False,
                )
            assert len(sleeps) == 2  # 3 attempts → 2 backoffs
        finally:
            es.close()
            blocker.close()


class TestKeyAuthedAdminRoutes:
    """Key auth guards /stop and /reload but never /queries.json
    (reference: ServerActor mixes KeyAuthentication into the admin
    routes only)."""

    @pytest.fixture()
    def authed_server(self, ctx, memory_storage):
        from predictionio_tpu.serving.config import ServerConfig

        run_train(
            _engine(), _params(), engine_id="srv-auth", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            _engine(),
            _params(),
            engine_id="srv-auth",
            storage=memory_storage,
            ctx=ctx,
            server_config=ServerConfig(
                key_auth_enforced=True, access_key="topsecret"
            ),
        )
        http = es.serve(host="127.0.0.1", port=0)
        http.start()
        yield f"http://127.0.0.1:{http.port}"
        http.shutdown()
        es.close()

    def test_queries_stay_open(self, authed_server):
        status, body = _call(
            f"{authed_server}/queries.json", "POST", {"x": 5}
        )
        assert status == 200 and body["result"] == 35

    def test_reload_requires_key(self, authed_server):
        status, _ = _call(f"{authed_server}/reload", "POST")
        assert status == 401
        status, _ = _call(
            f"{authed_server}/reload?accessKey=topsecret", "POST"
        )
        assert status == 200

    def test_stop_requires_key(self, authed_server):
        status, _ = _call(f"{authed_server}/stop", "POST")
        assert status == 401


class TestMicroBatcher:
    def test_batches_and_results_in_order(self):
        seen_batches = []

        def batch_fn(items):
            seen_batches.append(len(items))
            return [i * 2 for i in items]

        b = MicroBatcher(batch_fn, max_batch=16, max_wait_ms=20)
        futures = [b.submit(i) for i in range(40)]
        assert [f.result(5) for f in futures] == [i * 2 for i in range(40)]
        assert sum(seen_batches) == 40
        assert max(seen_batches) > 1  # some coalescing happened
        b.close()

    def test_error_propagates_to_all(self):
        def bad(items):
            raise RuntimeError("boom")

        b = MicroBatcher(bad, max_batch=4, max_wait_ms=1)
        futures = [b.submit(i) for i in range(3)]
        for f in futures:
            with pytest.raises(RuntimeError, match="boom"):
                f.result(5)
        b.close()

    def test_wrong_result_count(self):
        b = MicroBatcher(lambda items: [1], max_batch=4, max_wait_ms=1)
        f1, f2 = b.submit("a"), b.submit("b")
        with pytest.raises(RuntimeError, match="results"):
            f1.result(5)
        b.close()

    def test_submit_after_close(self):
        b = MicroBatcher(lambda items: items)
        b.close()
        with pytest.raises(RuntimeError):
            b.submit(1)


class TestReviewRegressions:
    def test_graceful_close_serves_queued_items(self):
        import time

        def slow(items):
            time.sleep(0.05)
            return [i * 2 for i in items]

        b = MicroBatcher(slow, max_batch=2, max_wait_ms=1)
        futures = [b.submit(i) for i in range(10)]
        b.close()  # must drain, not abandon
        assert [f.result(5) for f in futures] == [i * 2 for i in range(10)]

    def test_query_during_reload_survives(self, server, ctx, memory_storage):
        base, es, _ = server
        run_train(
            _engine(), _params(), engine_id="srv", ctx=ctx,
            storage=memory_storage,
        )
        errors = []
        done = threading.Event()

        def hammer():
            while not done.is_set():
                status, body = _call(
                    f"{base}/queries.json", "POST", {"x": 1}
                )
                if status != 200:
                    errors.append((status, body))

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        [t.start() for t in threads]
        for _ in range(3):
            _call(f"{base}/reload", "POST")
        done.set()
        [t.join() for t in threads]
        assert errors == []


class TestLoadShedding:
    """Overload sheds at the queue-depth bound with a fast 503 instead
    of queueing into a predict-timeout hang (VERDICT r1 weak #7)."""

    def test_batcher_overload_raises(self):
        import threading

        from predictionio_tpu.serving.batching import (
            BatcherOverloaded,
            MicroBatcher,
        )

        release = threading.Event()

        def slow_fn(items):
            release.wait(timeout=10)
            return items

        b = MicroBatcher(
            slow_fn, max_batch=1, max_wait_ms=0.1, max_queue=3
        )
        try:
            futures = [b.submit(i) for i in range(3)]
            # worker holds one batch; queue fills to the bound
            import time

            time.sleep(0.1)
            b.submit(99)  # qsize dropped by the in-flight item
            with pytest.raises(BatcherOverloaded):
                for _ in range(10):
                    b.submit(100)
            release.set()
            for f in futures:
                f.result(timeout=10)
        finally:
            release.set()
            b.close()

    def test_overload_maps_to_503(self, ctx, memory_storage):
        import threading

        run_train(
            _engine(), _params(), engine_id="srv-shed", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            _engine(),
            _params(),
            engine_id="srv-shed",
            storage=memory_storage,
            ctx=ctx,
            max_batch=1,
            max_queue=1,
            warmup=False,
        )
        # swap in a batcher whose work blocks, then overfill it
        release = threading.Event()
        from predictionio_tpu.serving.batching import MicroBatcher

        slow = MicroBatcher(
            lambda items: (release.wait(10), items)[1],
            max_batch=1, max_wait_ms=0.1, max_queue=1,
        )
        es._batchers = [slow]
        http = es.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            base = f"http://127.0.0.1:{http.port}"
            results = []

            def fire():
                results.append(
                    _call(f"{base}/queries.json", "POST", {"x": 1})[0]
                )

            threads = [
                threading.Thread(target=fire) for _ in range(6)
            ]
            for t in threads:
                t.start()
            import time

            time.sleep(0.3)
            release.set()
            for t in threads:
                t.join(timeout=15)
            assert 503 in results, results
        finally:
            release.set()
            http.shutdown()
            es.close()


class TestRemoteErrorLog:
    """--log-url (reference CreateServer.scala:446-457): serving
    failures POST a structured report to a remote collector."""

    def test_error_posts_to_log_url(self, ctx, memory_storage):
        import http.server
        import time

        received = []
        done = threading.Event()

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                received.append(
                    (self.path, self.rfile.read(length))
                )
                self.send_response(200)
                self.end_headers()
                done.set()

            def log_message(self, *a):
                pass

        sink = http.server.HTTPServer(("127.0.0.1", 0), Sink)
        threading.Thread(target=sink.serve_forever, daemon=True).start()
        run_train(
            _engine(), _params(), engine_id="logsrv", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            _engine(),
            _params(),
            engine_id="logsrv",
            storage=memory_storage,
            ctx=ctx,
            log_url=f"http://127.0.0.1:{sink.server_port}/collect",
            log_prefix="pio-",
        )
        http_srv = es.serve(host="127.0.0.1", port=0)
        http_srv.start()
        try:
            base = f"http://127.0.0.1:{http_srv.port}"
            # a non-object query fails validation inside the handler
            status, _ = _call(f"{base}/queries.json", "POST", [1, 2])
            assert status == 400
            assert done.wait(5), "no report reached the collector"
            path, payload = received[0]
            assert path == "/collect"
            report = json.loads(payload)
            assert report["message"].startswith("pio-")
            assert report["engineInstance"]["engineId"] == "logsrv"
            assert json.loads(report["query"]) == [1, 2]
            # a good query must NOT log
            done.clear()
            status, _ = _call(f"{base}/queries.json", "POST", {"x": 1})
            assert status == 200
            time.sleep(0.3)
            assert len(received) == 1
        finally:
            http_srv.shutdown()
            es.close()
            sink.shutdown()

    def test_bad_log_url_fails_at_deploy(self, ctx, memory_storage):
        run_train(
            _engine(), _params(), engine_id="badlog", ctx=ctx,
            storage=memory_storage,
        )
        with pytest.raises(ValueError, match="log-url"):
            EngineServer(
                _engine(), _params(), engine_id="badlog",
                storage=memory_storage, ctx=ctx,
                log_url="collector.internal/log",  # missing scheme
            )

    def test_close_stops_sender_and_truncates_large_queries(
        self, ctx, memory_storage
    ):
        import time

        run_train(
            _engine(), _params(), engine_id="trunc", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            _engine(), _params(), engine_id="trunc",
            storage=memory_storage, ctx=ctx,
            log_url="http://127.0.0.1:9/collect",  # unreachable
        )
        sender = [
            t for t in threading.enumerate()
            if t.name == "remote-error-log"
        ]
        assert len(sender) == 1  # started once, at init
        es.close()
        deadline = time.time() + 5
        while time.time() < deadline and sender[0].is_alive():
            time.sleep(0.05)
        assert not sender[0].is_alive(), "sender did not stop on close"
        # oversized failing query: the queued report is bounded (the
        # sender is stopped, so the payload stays observable)
        class FakeReq:
            body = b"[" + b"1," * 100_000 + b"1]"
        es._post_remote_log(ValueError("boom"), FakeReq())
        payload = es._log_queue.get_nowait()
        assert len(payload) < 8192
        assert b'"queryTruncated": true' in payload


class TestWarmupTelemetry:
    """Warmup visibility (docs/observability.md): per-bucket compile
    wall time + a cold/warm gauge a scrape can read."""

    def test_warmup_records_bucket_times_and_complete_gauge(
        self, ctx, memory_storage
    ):
        from predictionio_tpu.obs import MetricRegistry

        run_train(
            _engine(), _params(), engine_id="srv-warm", ctx=ctx,
            storage=memory_storage,
        )
        registry = MetricRegistry()
        es = EngineServer(
            _engine(), _params(), engine_id="srv-warm",
            storage=memory_storage, ctx=ctx, warmup=True,
            max_batch=8, registry=registry,
        )
        try:
            data = registry.to_dict()
            assert (
                data["pio_warmup_complete"]["samples"][0]["value"] == 1
            )
            samples = [
                s for s in data["pio_warmup_seconds"]["samples"]
                if s["labels"]["batcher"] == "srv-warm/algo0"
            ]
            assert {s["labels"]["bucket"] for s in samples} == {
                "1", "2", "4", "8"
            }
            for s in samples:
                assert s["value"] >= 0
        finally:
            es.close()

    def test_warmup_disabled_reports_cold(self, ctx, memory_storage):
        from predictionio_tpu.obs import MetricRegistry

        run_train(
            _engine(), _params(), engine_id="srv-cold", ctx=ctx,
            storage=memory_storage,
        )
        registry = MetricRegistry()
        es = EngineServer(
            _engine(), _params(), engine_id="srv-cold",
            storage=memory_storage, ctx=ctx, warmup=False,
            registry=registry,
        )
        try:
            data = registry.to_dict()
            assert (
                data["pio_warmup_complete"]["samples"][0]["value"] == 0
            )
        finally:
            es.close()


class TwoPhaseDictAlgorithm(DictQueryAlgorithm):
    """Dict-query algorithm speaking the two-phase serving protocol."""

    launches = 0
    collects = 0

    def batch_predict_launch(self, model, queries):
        type(self).launches += 1
        return [self.predict(model, q) for q in queries]

    def batch_predict_collect(self, model, handle, queries):
        type(self).collects += 1
        assert len(handle) == len(queries)
        return handle


class TestTwoPhaseServing:
    def test_two_phase_algorithm_rides_the_pipeline(
        self, ctx, memory_storage
    ):
        """An algorithm overriding batch_predict_launch must be served
        through dispatch/collect, not the single-phase fallback."""
        engine = Engine(
            FakeDataSource, FakePreparator, TwoPhaseDictAlgorithm,
            DictServing,
        )
        run_train(
            engine, _params(), engine_id="srv-2p", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            engine, _params(), engine_id="srv-2p",
            storage=memory_storage, ctx=ctx, warmup=False,
        )
        TwoPhaseDictAlgorithm.launches = 0
        TwoPhaseDictAlgorithm.collects = 0
        try:
            out = es._batchers[0].submit({"x": 4}).result(5)
            assert out == {"result": 34}
            assert TwoPhaseDictAlgorithm.launches >= 1
            assert TwoPhaseDictAlgorithm.collects >= 1
        finally:
            es.close()

    def test_half_override_falls_back_to_single_phase(
        self, ctx, memory_storage, caplog
    ):
        """Overriding only batch_predict_launch must not wire a broken
        half-protocol into the pipeline — single-phase fallback with a
        load-time warning instead of per-request NotImplementedError."""

        class HalfAlgorithm(DictQueryAlgorithm):
            def batch_predict_launch(self, model, queries):
                return queries

        engine = Engine(
            FakeDataSource, FakePreparator, HalfAlgorithm, DictServing
        )
        run_train(
            engine, _params(), engine_id="srv-half", ctx=ctx,
            storage=memory_storage,
        )
        import logging

        with caplog.at_level(
            logging.WARNING, "predictionio_tpu.serving.engine_server"
        ):
            es = EngineServer(
                engine, _params(), engine_id="srv-half",
                storage=memory_storage, ctx=ctx, warmup=False,
            )
        try:
            assert any(
                "single-phase" in r.message for r in caplog.records
            )
            out = es._batchers[0].submit({"x": 2}).result(5)
            assert out == {"result": 32}
        finally:
            es.close()


class TestWarmupFailureGauge:
    def test_all_failed_warmup_reports_cold(self, ctx, memory_storage):
        """pio_warmup_complete must stay 0 when every bucket compile
        failed — a traffic gate reading 1 would route load to a fully
        cold server."""
        from predictionio_tpu.obs import MetricRegistry

        class BrokenWarmup(DictQueryAlgorithm):
            def batch_predict(self, model, queries):
                raise RuntimeError("no shape compiles")

        engine = Engine(
            FakeDataSource, FakePreparator, BrokenWarmup, DictServing
        )
        run_train(
            engine, _params(), engine_id="srv-broken", ctx=ctx,
            storage=memory_storage,
        )
        registry = MetricRegistry()
        es = EngineServer(
            engine, _params(), engine_id="srv-broken",
            storage=memory_storage, ctx=ctx, warmup=True, max_batch=4,
            registry=registry,
        )
        try:
            data = registry.to_dict()
            assert (
                data["pio_warmup_complete"]["samples"][0]["value"] == 0
            )
        finally:
            es.close()


class TestCanaryCasRegressions:
    """PR 12 regression: canary-slot installs and clears happen under
    ``EngineServer._lock`` as a compare-and-set — a verdict applier
    finishing late must never clobber a newer canary, and close() must
    snapshot the canary + serving batchers in one locked step."""

    class _StubCanary:
        def __init__(self):
            self.closed = False
            self.staged = None
            self.retained = None

        def to_dict(self):
            return {"stub": True}

        def close(self):
            self.closed = True

    def test_late_verdict_never_clobbers_newer_canary(
        self, server, ctx, memory_storage
    ):
        _, es, _ = server
        newer, older = self._StubCanary(), self._StubCanary()
        es._canary = newer
        es._finish_canary(older)  # late applier from a prior reload
        assert es._canary is newer
        es._finish_canary(newer)  # the CURRENT canary clears normally
        assert es._canary is None

    def test_close_takes_and_clears_the_canary_snapshot(
        self, ctx, memory_storage
    ):
        run_train(
            _engine(), _params(), engine_id="srv-cas", ctx=ctx,
            storage=memory_storage,
        )
        es = EngineServer(
            _engine(), _params(), engine_id="srv-cas",
            storage=memory_storage, ctx=ctx,
        )
        canary = self._StubCanary()
        es._canary = canary
        es.close()
        assert canary.closed
        assert es._canary is None
