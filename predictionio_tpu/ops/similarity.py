"""Scoring / similarity kernels for serving.

Replaces the reference's per-query RDD predict (ALSAlgorithm.predict:
``productFeatures.lookup`` + cosine ``collect`` — a Spark job per query,
the serving anti-pattern SURVEY.md §3.2 flags) with pre-compiled dense
scoring: one [B, k] × [k, I] matmul + ``lax.top_k``. The same kernels
serve the recommendation template (dot-product scores) and the
similar-product template (cosine over item factors,
examples/scala-parallel-similarproduct/multi/.../ALSAlgorithm.scala).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

# the fused pallas kernel wins once XLA's [B, I] score intermediate gets
# big enough to dominate HBM traffic (measured crossover ~0.5 GB on v5e:
# B=256×I=1M pallas 20 ms vs xla 25 ms; below it XLA's fused top-k is
# slightly faster and pallas dispatch overhead isn't worth it)
_PALLAS_MIN_INTERMEDIATE_BYTES = 512 * 1024 * 1024


@jax.jit
def l2_normalize(x: jax.Array, eps: float = 1e-9) -> jax.Array:
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)


@partial(jax.jit, static_argnames=("num",))
def _top_k_dot_xla(
    queries: jax.Array,      # [B, k]
    items: jax.Array,        # [I, k]
    num: int,
    mask: jax.Array | None = None,  # [B, I] True = exclude
) -> tuple[jax.Array, jax.Array]:
    scores = queries @ items.T  # [B, I] — MXU
    # NaN scores (corrupted factors) map to -inf, matching the Pallas
    # kernel's masking — both top_k_dot paths must rank identically
    scores = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
    if mask is not None:
        scores = jnp.where(mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, num)


def _use_pallas(batch: int, n_items: int) -> bool:
    override = os.environ.get("PIO_PALLAS_TOPK")
    if override is not None:
        return override.strip().lower() in {"1", "true", "yes", "on"}
    # compiled Mosaic kernels exist only for TPU; every other backend
    # would hit the (slow) interpreter, so never auto-select it there
    return (
        batch * n_items * 4 >= _PALLAS_MIN_INTERMEDIATE_BYTES
        and jax.default_backend() == "tpu"
    )


def top_k_dot(
    queries: jax.Array,
    items: jax.Array,
    num: int,
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-``num`` items by dot product. Returns (scores, indices) [B, num].

    Large batch×catalog products on TPU take the fused Pallas path
    (:func:`predictionio_tpu.ops.pallas_topk.fused_top_k_dot`), which
    streams item blocks through VMEM instead of writing the [B, I]
    score matrix to HBM. ``PIO_PALLAS_TOPK=0/1`` overrides the choice."""
    num = min(num, items.shape[0])  # same clamp on both paths
    if _use_pallas(queries.shape[0], items.shape[0]):
        from predictionio_tpu.ops.pallas_topk import fused_top_k_dot

        # a forced override off-TPU runs the interpreter (slow but
        # correct); Mosaic kernels only compile for TPU
        return fused_top_k_dot(
            queries, items, num, mask,
            interpret=jax.default_backend() != "tpu",
        )
    return _top_k_dot_xla(queries, items, num, mask)


def top_k_cosine(
    queries: jax.Array,
    items: jax.Array,
    num: int,
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-``num`` by cosine similarity (similar-product scoring)."""
    return top_k_dot(
        l2_normalize(queries), l2_normalize(items), num, mask
    )
