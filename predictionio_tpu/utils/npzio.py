"""Atomic npz persistence shared by the view cache and event export."""

from __future__ import annotations

import os

import numpy as np


def atomic_savez(path: str, **arrays) -> None:
    """``np.savez_compressed`` that lands at ``path`` via rename, so
    readers never observe a half-written file. Parent dirs are created.
    """
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    np.savez_compressed(tmp, **arrays)
    # np.savez appends .npz to the tmp name
    os.replace(f"{tmp}.npz", path)
