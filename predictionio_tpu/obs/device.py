"""Device runtime telemetry: background HBM/live-array sampler and
jit compile/retrace counters.

``DeviceSampler`` runs a daemon thread that periodically reads
``device.memory_stats()`` for every local accelerator and publishes

* ``pio_device_hbm_used_bytes{device}`` / ``pio_device_hbm_limit_bytes{device}``
* ``pio_device_live_array_bytes`` — bytes held by live jax arrays in
  this process (the host-side view of model + batch residency)

``CompileTracker`` counts jit compilation work at instrumented call
sites (the engine server's warm-up buckets, the trainer's step fn):
``pio_jit_compiles_total{site}`` on every new trace signature and
``pio_jit_retraces_total{site}`` when a site that already compiled
sees a *different* signature — the "shape churn is recompiling the
model" smell.

The module is import-safe without jax (``obs/`` stays stdlib-only at
import time): jax is imported lazily inside the sampler, and backends
without memory stats (CPU CI) degrade to a clean no-op — the thread
keeps its cadence but publishes nothing.
"""

from __future__ import annotations

import os
import threading
from typing import Callable

from predictionio_tpu.obs.registry import MetricRegistry

_MIN_SAMPLE_S = 0.05


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def sample_devices() -> dict:
    """One synchronous read of per-device HBM stats and live-array
    bytes. Returns ``{"devices": {label: {"used": .., "limit": ..}},
    "liveArrayBytes": float}`` — empty devices dict on backends
    without memory stats, ``{}`` entirely when jax is unavailable."""
    try:
        import jax
    except Exception:
        return {}
    devices = {}
    try:
        local = jax.local_devices()
    except Exception:
        local = []
    for device in local:
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit"
        )
        if used is None:
            continue
        label = f"{device.platform}:{device.id}"
        devices[label] = {
            "used": float(used),
            "limit": float(limit) if limit is not None else None,
        }
    live = 0.0
    try:
        for arr in jax.live_arrays():
            live += float(getattr(arr, "nbytes", 0) or 0)
    except Exception:
        live = 0.0
    return {"devices": devices, "liveArrayBytes": live}


class DeviceSampler:
    """Daemon thread publishing device HBM gauges on a fixed cadence
    (``PIO_DEVICE_SAMPLE_S``, default 10 s, monotonic clock via
    ``Event.wait``). ``start`` takes an eager first sample so gauges
    are live before the first tick; ``stop`` joins the thread."""

    def __init__(
        self,
        registry: MetricRegistry,
        *,
        interval_s: float | None = None,
        sample_fn: Callable[[], dict] = sample_devices,
    ) -> None:
        self._interval_s = max(
            _MIN_SAMPLE_S,
            interval_s
            if interval_s is not None
            else _env_float("PIO_DEVICE_SAMPLE_S", 10.0),
        )
        self._sample_fn = sample_fn
        self._used = registry.gauge(
            "pio_device_hbm_used_bytes",
            "Device HBM bytes in use (device.memory_stats)",
            ("device",),
        )
        self._limit = registry.gauge(
            "pio_device_hbm_limit_bytes",
            "Device HBM capacity bytes (device.memory_stats)",
            ("device",),
        )
        self._live = registry.gauge(
            "pio_device_live_array_bytes",
            "Bytes held by live jax arrays in this process",
        )
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._last: dict = {}

    def sample_once(self) -> dict:
        """Take and publish one sample; returns what was read (the
        profile-capture artifact snapshots this)."""
        sample = self._sample_fn() or {}
        for label, stats in (sample.get("devices") or {}).items():
            self._used.labels(label).set(stats.get("used") or 0.0)
            if stats.get("limit") is not None:
                self._limit.labels(label).set(stats["limit"])
        if "liveArrayBytes" in sample:
            self._live.set(sample["liveArrayBytes"])
        with self._lock:
            self._last = sample
        return sample

    def last_sample(self) -> dict:
        with self._lock:
            return dict(self._last)

    def start(self) -> "DeviceSampler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stopped.clear()
            thread = threading.Thread(
                target=self._run,
                name="pio-device-sampler",
                daemon=True,
            )
            self._thread = thread
        try:
            self.sample_once()
        except Exception:
            pass  # eager sample is best-effort; cadence still starts
        thread.start()
        return self

    def _run(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.sample_once()
            except Exception:
                continue  # a flaky backend read must not kill cadence

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)


class CompileTracker:
    """Counts jit compile work at named call sites. ``record(site,
    signature)`` increments ``pio_jit_compiles_total{site}`` for every
    signature the site has not traced before, and additionally
    ``pio_jit_retraces_total{site}`` when the site had already
    compiled a *different* signature (shape churn). Re-recording a
    known signature is a no-op — cache hits are free."""

    def __init__(self, registry: MetricRegistry) -> None:
        self._compiles = registry.counter(
            "pio_jit_compiles_total",
            "jit trace compilations per instrumented site",
            ("site",),
        )
        self._retraces = registry.counter(
            "pio_jit_retraces_total",
            "jit recompilations of an already-compiled site with a "
            "new signature",
            ("site",),
        )
        self._lock = threading.Lock()
        self._seen: dict[str, set] = {}

    def record(self, site: str, signature) -> bool:
        """Returns True when this (site, signature) compiled fresh."""
        key = repr(signature)
        with self._lock:
            seen = self._seen.setdefault(site, set())
            if key in seen:
                return False
            retrace = bool(seen)
            seen.add(key)
        self._compiles.labels(site).inc()
        if retrace:
            self._retraces.labels(site).inc()
        return True
