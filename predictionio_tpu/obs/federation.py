"""Fleet metrics federation: merge per-replica ``/metrics.json``
payloads into one scrape surface.

The serving router scrapes every replica's ``/metrics.json`` and
re-exposes the whole fleet from its own ``/metrics`` — one scrape sees
every replica (each series labeled ``replica=<id>``) plus an exactly
merged fleet view:

* **counters** merge by sum — cumulative totals add across processes;
* **histograms** merge by bucket-wise sum over the raw per-bucket
  counts the registry snapshot carries (including the explicit
  ``+Inf`` overflow bucket), so fleet percentiles are re-derived from
  the merged distribution, never averaged from per-replica
  percentiles (averaging percentiles is the classic federation bug
  this module exists to avoid);
* **gauges** are NOT merged — a sum of ``pio_model_generation`` means
  nothing. They stay visible per replica (``replica`` label) and the
  router exports its own fleet-level gauges (``pio_fleet_*``,
  ``pio_slo_*``) beside them.

Stdlib-only, like the rest of ``obs/`` — the router imports this, not
the other way around.
"""

from __future__ import annotations

import math

from predictionio_tpu.obs.registry import _fmt, _nan_none, _quantile

#: label the router injects into every federated replica series
REPLICA_LABEL = "replica"


def _finite_bounds(samples: list[dict]) -> tuple[float, ...]:
    bounds: set[float] = set()
    for sample in samples:
        for key in (sample.get("buckets") or {}):
            if key != "+Inf":
                try:
                    bounds.add(float(key))
                except ValueError:
                    continue
    return tuple(sorted(bounds))


def merge_histogram_samples(samples: list[dict]) -> dict:
    """Bucket-wise sum of registry histogram snapshots (same labels,
    different replicas); percentiles re-derived from the merged
    buckets. Pre-``+Inf`` snapshots degrade gracefully: the overflow
    count is reconstructed as ``count - sum(finite buckets)``."""
    bounds = _finite_bounds(samples)
    counts = [0] * (len(bounds) + 1)
    total = 0
    total_sum = 0.0
    for sample in samples:
        buckets = sample.get("buckets") or {}
        finite = 0
        for i, bound in enumerate(bounds):
            c = int(buckets.get(_fmt(bound), 0) or 0)
            counts[i] += c
            finite += c
        count = int(sample.get("count", 0) or 0)
        overflow = buckets.get("+Inf")
        if overflow is None:
            overflow = max(0, count - finite)
        counts[-1] += int(overflow)
        total += count
        total_sum += float(sample.get("sum", 0.0) or 0.0)
    merged = {_fmt(b): c for b, c in zip(bounds, counts)}
    merged["+Inf"] = counts[-1]
    return {
        "count": total,
        "sum": round(total_sum, 6),
        "buckets": merged,
        "p50": _nan_none(_quantile(bounds, counts, total, 0.50)),
        "p95": _nan_none(_quantile(bounds, counts, total, 0.95)),
        "p99": _nan_none(_quantile(bounds, counts, total, 0.99)),
    }


def _label_key(sample: dict) -> tuple:
    return tuple(sorted((sample.get("labels") or {}).items()))


def merge_payloads(payloads: dict[str, dict]) -> dict:
    """Merge per-replica ``/metrics.json`` payloads into one fleet
    view: counters summed and histograms bucket-wise summed per
    label set; gauges (and unknown kinds) dropped — see module doc."""
    families: dict[str, dict] = {}
    for rid in sorted(payloads):
        payload = payloads[rid]
        if not isinstance(payload, dict):
            continue
        for name, family in payload.items():
            if not isinstance(family, dict):
                continue
            kind = family.get("type")
            if kind not in ("counter", "histogram"):
                continue
            fam = families.setdefault(
                name,
                {
                    "type": kind,
                    "help": family.get("help", ""),
                    "groups": {},
                },
            )
            if fam["type"] != kind:
                continue  # conflicting registrations: first one wins
            for sample in family.get("samples", ()):
                if not isinstance(sample, dict):
                    continue
                fam["groups"].setdefault(_label_key(sample), []).append(
                    sample
                )
    out: dict[str, dict] = {}
    for name in sorted(families):
        fam = families[name]
        samples = []
        for key in sorted(fam["groups"]):
            group = fam["groups"][key]
            labels = dict(key)
            if fam["type"] == "histogram":
                samples.append(
                    {"labels": labels, **merge_histogram_samples(group)}
                )
            else:
                samples.append(
                    {
                        "labels": labels,
                        "value": sum(
                            float(s.get("value") or 0.0) for s in group
                        ),
                    }
                )
        out[name] = {
            "type": fam["type"],
            "help": fam["help"],
            "samples": samples,
        }
    return out


def combine_families(
    local: dict, payloads: dict[str, dict]
) -> dict:
    """Family-union of the router's own registry dict and every
    replica payload, each replica sample gaining a ``replica`` label —
    the per-series federated view (no merging, no double counting)."""
    combined: dict[str, dict] = {}
    for name, family in local.items():
        combined[name] = {
            "type": family.get("type"),
            "help": family.get("help", ""),
            "samples": list(family.get("samples", ())),
        }
    for rid in sorted(payloads):
        payload = payloads[rid]
        if not isinstance(payload, dict):
            continue
        for name, family in payload.items():
            if not isinstance(family, dict):
                continue
            fam = combined.setdefault(
                name,
                {
                    "type": family.get("type"),
                    "help": family.get("help", ""),
                    "samples": [],
                },
            )
            for sample in family.get("samples", ()):
                if not isinstance(sample, dict):
                    continue
                fam["samples"].append(
                    {
                        **sample,
                        "labels": {
                            **(sample.get("labels") or {}),
                            REPLICA_LABEL: rid,
                        },
                    }
                )
    return combined


def _render_labels(labels: dict) -> str:
    if not labels:
        return ""
    pairs = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + pairs + "}"


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _render_value(value) -> str:
    if value is None:
        return "NaN"
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v.is_integer():
        return str(int(v))
    return repr(v)


def render_prometheus_families(families: dict) -> str:
    """Prometheus text exposition 0.0.4 over the JSON family shape —
    the federated equivalent of ``MetricRegistry.render_prometheus``
    (one HELP/TYPE per family even when samples come from many
    replicas, cumulative ``_bucket`` series rebuilt from raw bucket
    counts)."""
    lines: list[str] = []
    for name in sorted(families):
        family = families[name]
        kind = family.get("type") or "untyped"
        lines.append(f"# HELP {name} {family.get('help', '')}")
        lines.append(f"# TYPE {name} {kind}")
        for sample in family.get("samples", ()):
            labels = dict(sample.get("labels") or {})
            if kind == "histogram":
                buckets = sample.get("buckets") or {}
                bounds = _finite_bounds([sample])
                cumulative = 0
                for bound in bounds:
                    cumulative += int(buckets.get(_fmt(bound), 0) or 0)
                    le = _render_labels({**labels, "le": _fmt(bound)})
                    lines.append(f"{name}_bucket{le} {cumulative}")
                count = int(sample.get("count", 0) or 0)
                le = _render_labels({**labels, "le": "+Inf"})
                lines.append(f"{name}_bucket{le} {count}")
                label_str = _render_labels(labels)
                lines.append(
                    f"{name}_sum{label_str} "
                    f"{_render_value(sample.get('sum', 0.0))}"
                )
                lines.append(f"{name}_count{label_str} {count}")
            else:
                label_str = _render_labels(labels)
                lines.append(
                    f"{name}{label_str} "
                    f"{_render_value(sample.get('value'))}"
                )
    return "\n".join(lines) + "\n"


def counter_total(families: dict, name: str, **labels) -> float:
    """Sum a counter family's samples across every label set matching
    ``labels`` — the federation consumer's rollup read (fleet goodput,
    fleet SLO ingestion)."""
    total = 0.0
    family = families.get(name)
    if not isinstance(family, dict):
        return total
    for sample in family.get("samples", ()):
        sample_labels = sample.get("labels") or {}
        if all(sample_labels.get(k) == v for k, v in labels.items()):
            try:
                total += float(sample.get("value") or 0.0)
            except (TypeError, ValueError):
                continue
    return total
