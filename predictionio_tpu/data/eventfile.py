"""Columnar event interchange files.

The reference's ``pio export`` writes either JSON lines or **parquet**
(``tools/.../export/EventsToFile.scala:40-104``, format flag at
``Console.scala:604-618``) and ``pio import`` reads them back
(``imprt/FileToEvents.scala:41-103``). The TPU build's columnar
interchange format is a compressed ``.npz`` of per-field numpy columns
— the same container :mod:`predictionio_tpu.data.view` uses for cached
views, but with **full event fidelity** (tags, prId, event ids,
creation times — everything the DB serializer round-trips), so
``export → import`` reproduces the event log exactly.

Hot string fields are real columns (scan a column without touching the
rest — the property parquet buys the reference); variable-shape fields
(properties, tags) travel as JSON-encoded string columns. Times are
ISO-8601 strings to preserve timezones bit-for-bit with the JSON-lines
format. ``allow_pickle`` stays False on read: untrusted export files
must not execute code.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
from typing import Iterable, Iterator

import numpy as np

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event

#: bumped on layout changes; readers reject files they don't understand
FORMAT_VERSION = 1


def write_events_npz(events: Iterable[Event], path: str) -> int:
    """Write events as a columnar npz; returns the row count.

    Atomic: lands under a temp name first, like the view cache.
    """
    ev, ety, eid, tty, tid = [], [], [], [], []
    t_event, t_creation, props, tags, pr, ids = [], [], [], [], [], []
    for e in events:
        ev.append(e.event)
        ety.append(e.entity_type)
        eid.append(e.entity_id)
        tty.append(e.target_entity_type or "")
        tid.append(e.target_entity_id or "")
        t_event.append(e.event_time.isoformat())
        t_creation.append(e.creation_time.isoformat())
        props.append(json.dumps(e.properties.to_dict()))
        tags.append(json.dumps(list(e.tags)))
        pr.append(e.pr_id or "")
        ids.append(e.event_id or "")
    from predictionio_tpu.utils.npzio import atomic_savez

    atomic_savez(
        path,
        format_version=np.asarray([FORMAT_VERSION], dtype=np.int64),
        event=np.asarray(ev, dtype=np.str_),
        entity_type=np.asarray(ety, dtype=np.str_),
        entity_id=np.asarray(eid, dtype=np.str_),
        target_entity_type=np.asarray(tty, dtype=np.str_),
        target_entity_id=np.asarray(tid, dtype=np.str_),
        event_time=np.asarray(t_event, dtype=np.str_),
        creation_time=np.asarray(t_creation, dtype=np.str_),
        properties=np.asarray(props, dtype=np.str_),
        tags=np.asarray(tags, dtype=np.str_),
        pr_id=np.asarray(pr, dtype=np.str_),
        event_id=np.asarray(ids, dtype=np.str_),
    )
    return len(ev)


def read_events_npz(path: str) -> Iterator[Event]:
    """Yield events from a columnar npz written by
    :func:`write_events_npz`."""
    with np.load(path, allow_pickle=False) as z:
        names = set(z.files)
        if "format_version" not in names:
            raise ValueError(
                f"{path} is not an event export (no format_version)"
            )
        version = int(z["format_version"][0])
        if version != FORMAT_VERSION:
            raise ValueError(
                f"{path}: unsupported event-file version {version} "
                f"(this build reads {FORMAT_VERSION})"
            )
        cols = {
            name: z[name]
            for name in (
                "event",
                "entity_type",
                "entity_id",
                "target_entity_type",
                "target_entity_id",
                "event_time",
                "creation_time",
                "properties",
                "tags",
                "pr_id",
                "event_id",
            )
        }
    for i in range(len(cols["event"])):
        yield Event(
            event=str(cols["event"][i]),
            entity_type=str(cols["entity_type"][i]),
            entity_id=str(cols["entity_id"][i]),
            target_entity_type=str(cols["target_entity_type"][i]) or None,
            target_entity_id=str(cols["target_entity_id"][i]) or None,
            properties=DataMap(json.loads(str(cols["properties"][i]))),
            event_time=_dt.datetime.fromisoformat(
                str(cols["event_time"][i])
            ),
            creation_time=_dt.datetime.fromisoformat(
                str(cols["creation_time"][i])
            ),
            tags=tuple(json.loads(str(cols["tags"][i]))),
            pr_id=str(cols["pr_id"][i]) or None,
            event_id=str(cols["event_id"][i]) or None,
        )
