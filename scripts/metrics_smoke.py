"""Telemetry smoke test: deploy a fake engine in-process, scrape
``/metrics``, verify request-ID echo, and pull ``/debug/traces`` to
assert a non-empty Perfetto-valid trace — run by ``scripts/check.sh``
so a telemetry regression fails fast without waiting on the full suite.

Part two federates: two REAL replica processes behind an in-process
serving router, proving the fleet-merged counters exactly equal the
sum of the per-replica scrapes, every replica series carries its
``replica`` label, and a SIGKILLed replica turns stale (marked, last
snapshot retained) instead of vanishing from the fleet view.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # the package itself (no install required)
sys.path.insert(0, os.path.join(REPO, "tests"))  # fake_engine fixture


def main() -> int:
    from fake_engine import (
        FakeAlgorithm,
        FakeDataSource,
        FakeParams,
        FakePreparator,
        FakeServing,
    )
    from predictionio_tpu.core import Engine, EngineParams
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data.storage import Storage, set_storage
    from predictionio_tpu.parallel.mesh import ComputeContext
    from predictionio_tpu.serving.engine_server import EngineServer

    class SmokeAlgorithm(FakeAlgorithm):
        def predict(self, model, query):
            return {"result": int(query.get("x", 0))}

        def batch_predict(self, model, queries):
            return [self.predict(model, q) for q in queries]

    class SmokeServing(FakeServing):
        def serve(self, query, predictions):
            return predictions[0]

    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    set_storage(storage)
    engine = Engine(
        FakeDataSource, FakePreparator, SmokeAlgorithm, SmokeServing
    )
    params = EngineParams(
        data_source=("", FakeParams(id=1)),
        preparator=("", FakeParams(id=2)),
        algorithms=[("", FakeParams(id=3))],
        serving=("", FakeParams()),
    )
    ctx = ComputeContext.create(batch="metrics-smoke")
    run_train(
        engine, params, engine_id="smoke", ctx=ctx, storage=storage
    )
    server = EngineServer(
        engine, params, engine_id="smoke", storage=storage, ctx=ctx,
        warmup=False,
    )
    http = server.serve(host="127.0.0.1", port=0)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    failures: list[str] = []

    def check(cond: bool, label: str) -> None:
        print(("ok   " if cond else "FAIL ") + label)
        if not cond:
            failures.append(label)

    try:
        req = urllib.request.Request(
            f"{base}/queries.json",
            data=json.dumps({"x": 7}).encode(),
            method="POST",
            headers={"X-Request-ID": "smoke-1"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            check(resp.status == 200, "query answered")
            check(
                resp.headers.get("X-Request-ID") == "smoke-1",
                "X-Request-ID echoed",
            )

        with urllib.request.urlopen(
            f"{base}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        for needle in (
            "pio_http_request_seconds_bucket",
            'route="/queries.json"',
            "pio_http_requests_total",
            "pio_batch_occupancy_bucket",
            "pio_batch_queue_depth",
            "pio_device_dispatch_seconds_bucket",
        ):
            check(needle in text, f"/metrics exposes {needle}")

        with urllib.request.urlopen(
            f"{base}/metrics.json", timeout=10
        ) as resp:
            data = json.load(resp)
        lat = data.get("pio_http_request_seconds", {})
        sample = next(
            (
                s for s in lat.get("samples", ())
                if s["labels"].get("route") == "/queries.json"
            ),
            None,
        )
        check(
            sample is not None and sample["p50"] is not None,
            "/metrics.json derives percentiles",
        )
        check(
            data.get("pio_train_step_seconds") is not None,
            "train-time StepTimer records joined the registry",
        )
        check(
            data.get("pio_build_info") is not None
            and data.get("pio_process_start_time_seconds") is not None,
            "build info + process start time gauges exposed",
        )

        # the tracing flight recorder: the query above must have left a
        # trace, and /debug/traces must be Perfetto-valid Chrome
        # trace-event JSON (loads at ui.perfetto.dev as-is)
        with urllib.request.urlopen(
            f"{base}/debug/traces", timeout=10
        ) as resp:
            trace = json.load(resp)
        events = trace.get("traceEvents")
        check(
            isinstance(events, list) and len(events) > 0,
            "/debug/traces returns a non-empty trace",
        )
        spans = [e for e in (events or []) if e.get("ph") == "X"]
        check(
            bool(spans)
            and all(
                isinstance(e.get("name"), str)
                and isinstance(e.get("ts"), (int, float))
                and isinstance(e.get("dur"), (int, float))
                and isinstance(e.get("pid"), int)
                for e in spans
            ),
            "/debug/traces events are Perfetto-valid complete events",
        )
        check(
            any(e["name"] == "batch_dispatch" for e in spans),
            "trace contains the linked batch_dispatch span",
        )
        check(
            any(
                e.get("args", {}).get("traceId") == "smoke-1"
                for e in spans
            ),
            "trace ID matches the forwarded X-Request-ID",
        )

        with urllib.request.urlopen(
            f"{base}/debug/traces.json", timeout=10
        ) as resp:
            raw = json.load(resp)
        check(
            bool(raw.get("traces"))
            and any(
                t["traceId"] == "smoke-1" for t in raw["traces"]
            ),
            "/debug/traces.json retains the raw span tree",
        )

        # mixed-tenant traffic: the X-PIO-Tenant identity must surface
        # as per-tenant cost series, and the summed attribution must
        # conserve the batcher's total measured device time (1%)
        for i in range(12):
            tenant = "tenant-a" if i % 3 else "tenant-b"
            req = urllib.request.Request(
                f"{base}/queries.json",
                data=json.dumps({"x": i}).encode(),
                method="POST",
                headers={"X-PIO-Tenant": tenant},
            )
            with urllib.request.urlopen(req, timeout=10):
                pass
        with urllib.request.urlopen(
            f"{base}/metrics.json", timeout=10
        ) as resp:
            data = json.load(resp)
        tenant_dev = {
            s["labels"]["tenant"]: s["value"]
            for s in data.get("pio_tenant_device_seconds_total", {}).get(
                "samples", ()
            )
        }
        check(
            {"tenant-a", "tenant-b"} <= set(tenant_dev),
            "per-tenant device-seconds series surface per X-PIO-Tenant",
        )
        measured = sum(
            s["sum"]
            for name in (
                "pio_device_enqueue_seconds",
                "pio_device_sync_seconds",
            )
            for s in data.get(name, {}).get("samples", ())
        )
        attributed = sum(tenant_dev.values())
        check(
            measured > 0
            and abs(attributed - measured) <= 0.01 * measured,
            f"tenant attribution conserves device time "
            f"({attributed:.6f}s vs {measured:.6f}s measured)",
        )
        tenant_req = {
            (s["labels"]["tenant"], s["labels"]["status"])
            for s in data.get("pio_tenant_requests_total", {}).get(
                "samples", ()
            )
        }
        check(
            ("tenant-a", "ok") in tenant_req,
            "pio_tenant_requests_total carries tenant+status labels",
        )

        # the incident timeline: every server serves its ring, opening
        # with the server_start marker
        with urllib.request.urlopen(
            f"{base}/debug/timeline.json", timeout=10
        ) as resp:
            ring = json.load(resp)
        check(
            any(
                e.get("kind") == "server_start"
                for e in ring.get("events", ())
            ),
            "/debug/timeline.json serves the process ring",
        )
    finally:
        http.shutdown()
        server.close()

    federation_section(failures)

    if failures:
        print(f"metrics smoke: {len(failures)} check(s) FAILED")
        return 1
    print("metrics smoke: all checks passed")
    return 0


def _spawn_replica(generation: str):
    """(proc, port): one REAL replica child process (SIGKILLable)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    child = os.path.join(REPO, "tests", "router_replica_child.py")
    proc = subprocess.Popen(
        [sys.executable, child, "--port", "0",
         "--generation", generation],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    bound: list[int] = []

    def _scan():
        for line in proc.stdout:
            if "listening on" in line and not bound:
                bound.append(
                    int(line.split("pid=")[0].rsplit(":", 1)[1])
                )
        # keep draining so request logs can't block the child

    threading.Thread(target=_scan, daemon=True).start()
    deadline = time.monotonic() + 120
    while not bound and time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"replica {generation} died at startup")
        time.sleep(0.1)
    if not bound:
        proc.kill()
        raise RuntimeError(f"replica {generation} never bound")
    return proc, bound[0]


def federation_section(failures: list[str]) -> None:
    """Two replica processes behind a router: exact counter merge,
    per-replica labels, SIGKILL staleness."""
    from predictionio_tpu.obs import MetricRegistry
    from predictionio_tpu.obs.federation import counter_total
    from predictionio_tpu.serving.router import ServingRouter

    def check(cond: bool, label: str) -> None:
        print(("ok   " if cond else "FAIL ") + label)
        if not cond:
            failures.append(label)

    proc_a, port_a = _spawn_replica("fed-a")
    proc_b, port_b = _spawn_replica("fed-b")
    router = ServingRouter(
        probe_interval_s=0.2, registry=MetricRegistry()
    )
    router.add_replica(f"http://127.0.0.1:{port_a}", replica_id="a")
    router.add_replica(f"http://127.0.0.1:{port_b}", replica_id="b")
    http = router.serve(host="127.0.0.1", port=0)
    http.start()
    base = f"http://127.0.0.1:{http.port}"
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"{base}/", timeout=10) as r:
                status = json.load(r)
            states = {
                rep["id"]: rep["state"]
                for rep in status.get("replicas", [])
            }
            if all(states.get(rid) == "healthy" for rid in ("a", "b")):
                break
            time.sleep(0.2)

        served = 0
        for i in range(24):
            # mixed tenants: the identity hops the router to the
            # replicas, whose attribution series then federate
            req = urllib.request.Request(
                f"{base}/queries.json",
                data=json.dumps({"x": i}).encode(),
                method="POST",
                headers={
                    "X-PIO-Tenant": (
                        "tenant-a" if i % 3 else "tenant-b"
                    )
                },
            )
            with urllib.request.urlopen(req, timeout=20) as resp:
                served += resp.status == 200
        check(served == 24, "24 queries served through the router")

        with urllib.request.urlopen(
            f"{base}/metrics.json", timeout=20
        ) as resp:
            fed = json.load(resp)
        replicas = sorted(fed["federation"]["replicas"])
        check(
            replicas == ["a", "b"],
            "federated scrape reaches both replicas",
        )
        check(
            fed["federation"]["stale"] == [],
            "no replica stale while both live",
        )
        name = "pio_http_requests_total"
        fleet_total = counter_total(fed["fleet"], name)
        per_replica = sum(
            counter_total(fed["perReplica"][rid], name)
            for rid in replicas
        )
        check(
            fleet_total == per_replica and fleet_total >= 24,
            f"fleet {name} ({fleet_total}) == sum of per-replica "
            f"scrapes ({per_replica})",
        )
        slo_total = counter_total(
            fed["fleet"], "pio_slo_requests_total", outcome="good"
        )
        check(
            slo_total >= 24,
            "fleet SLO good-request counter federates",
        )

        with urllib.request.urlopen(
            f"{base}/metrics", timeout=20
        ) as resp:
            text = resp.read().decode()
        check(
            'replica="a"' in text,
            "federated text carries replica=a labels",
        )
        check(
            'replica="b"' in text,
            "federated text carries replica=b labels",
        )
        check(
            text.count(f"# TYPE {name} counter") == 1,
            "one TYPE line per federated family",
        )
        check(
            "pio_fleet_goodput_qps" in text
            and "pio_slo_burn_rate" in text,
            "fleet rollup gauges exported beside replica series",
        )

        fleet_tenants = {
            s["labels"]["tenant"]
            for s in fed["fleet"]
            .get("pio_tenant_device_seconds_total", {})
            .get("samples", ())
        }
        check(
            {"tenant-a", "tenant-b"} <= fleet_tenants,
            "per-tenant cost series federate fleet-wide",
        )
        fleet_measured = sum(
            s["sum"]
            for name in (
                "pio_device_enqueue_seconds",
                "pio_device_sync_seconds",
            )
            for s in fed["fleet"].get(name, {}).get("samples", ())
        )
        fleet_attributed = sum(
            s["value"]
            for s in fed["fleet"]
            .get("pio_tenant_device_seconds_total", {})
            .get("samples", ())
        )
        check(
            fleet_measured > 0
            and abs(fleet_attributed - fleet_measured)
            <= 0.01 * fleet_measured,
            f"fleet tenant attribution conserves device time "
            f"({fleet_attributed:.6f}s vs {fleet_measured:.6f}s)",
        )

        # merged incident timeline, both replicas live: the per-replica
        # rings plus the router's own, one wall-ordered narrative
        with urllib.request.urlopen(
            f"{base}/debug/timeline.json", timeout=20
        ) as resp:
            tl1 = json.load(resp)
        by_replica = {
            e.get("replica")
            for e in tl1.get("events", ())
            if e.get("kind") == "server_start"
        }
        check(
            {"a", "b"} <= by_replica,
            "merged timeline carries both replicas' rings",
        )
        check(
            "router" in tl1.get("replicas", ())
            and any(
                e.get("kind") == "replica_registered"
                for e in tl1.get("events", ())
            ),
            "router's own membership events join the merge",
        )
        walls = [e.get("wall", 0.0) for e in tl1.get("events", ())]
        check(
            walls == sorted(walls) and len(walls) > 0,
            "merged timeline events are wall-clock ordered",
        )
        check(tl1.get("stale") == [], "no timeline stale while both live")

        print(f"SIGKILL replica b (pid {proc_b.pid})", flush=True)
        os.kill(proc_b.pid, signal.SIGKILL)
        proc_b.wait(timeout=30)
        with urllib.request.urlopen(
            f"{base}/metrics.json", timeout=20
        ) as resp:
            fed2 = json.load(resp)
        check(
            "b" in fed2["federation"]["replicas"]
            and "b" in fed2["federation"]["stale"],
            "SIGKILLed replica marked stale, not dropped",
        )
        b_total = counter_total(fed2["perReplica"].get("b", {}), name)
        check(
            b_total > 0,
            "stale replica still contributes its last snapshot",
        )
        stale_marker = counter_total(
            {"s": fed2["local"]["pio_federation_stale"]}, "s",
            replica="b",
        )
        check(
            stale_marker == 1.0,
            "pio_federation_stale{replica=b} == 1",
        )

        # the SIGKILLed replica's timeline: stale, not absent — its
        # final events stay in the merged narrative, still in order
        with urllib.request.urlopen(
            f"{base}/debug/timeline.json", timeout=20
        ) as resp:
            tl2 = json.load(resp)
        check(
            "b" in tl2.get("stale", ())
            and "b" in tl2.get("replicas", ()),
            "SIGKILLed replica's timeline marked stale, not dropped",
        )
        check(
            any(
                e.get("replica") == "b"
                and e.get("kind") == "server_start"
                for e in tl2.get("events", ())
            ),
            "dead replica's last timeline snapshot still contributes",
        )
        walls2 = [e.get("wall", 0.0) for e in tl2.get("events", ())]
        check(
            walls2 == sorted(walls2),
            "merged timeline stays wall-ordered across the kill",
        )
    finally:
        http.shutdown()
        router.close()
        for proc in (proc_a, proc_b):
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=30)


if __name__ == "__main__":
    sys.exit(main())
