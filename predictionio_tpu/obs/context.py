"""Request-ID propagation.

Every inbound HTTP request gets (or forwards, via ``X-Request-ID``) an
ID held in a :class:`contextvars.ContextVar`. The serving stack is
thread-per-request with synchronous handlers, so the contextvar rides
the handler thread end-to-end: the micro-batcher reads it at submit
time and carries it into the device-dispatch log line, which is what
makes one slow query traceable through the batcher to the device step.
"""

from __future__ import annotations

import contextvars
import json
import logging
import re
import secrets
import time

_request_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_request_id", default=None
)

#: forwarded IDs (X-Request-ID, X-Parent-Span) are clamped to this
#: shape so a hostile header cannot smuggle log-breaking bytes or
#: unbounded cardinality into log lines or traces — ONE pattern for
#: request-ID and span-ID validation, so acceptance cannot drift
ID_OK = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")


def new_request_id() -> str:
    return secrets.token_hex(8)


def set_request_id(request_id: str | None) -> str:
    """Install ``request_id`` (sanitized) for the current context,
    minting a fresh one when absent or malformed; returns the ID."""
    if not request_id or not ID_OK.match(request_id):
        request_id = new_request_id()
    _request_id.set(request_id)
    return request_id


def get_request_id() -> str | None:
    return _request_id.get()


#: keys travel in query strings for reference parity; they must never
#: land in logs, terminals, or CI output — one regex, shared by the
#: HTTP access log and the CLI, so the rule cannot drift
_ACCESS_KEY = re.compile(r"(accessKey=)[^&\s\"]+")


def redact_keys(text: str) -> str:
    """Blank accessKey values out of a URL or log line."""
    return _ACCESS_KEY.sub(r"\1[redacted]", text)


#: keys every structured line owns; caller fields must not shadow them
#: (log pipelines key on `event`, and a spoofed `requestId` would break
#: the correlation the header propagation exists for)
_RESERVED_KEYS = ("event", "ts", "requestId")


def log_json(
    logger: logging.Logger, level: int, event: str, /, **fields
) -> None:
    """One structured JSON log line, request ID included when present.

    Rendered eagerly only when the level is enabled — the hot path pays
    an ``isEnabledFor`` check, not a ``json.dumps``. Caller fields that
    collide with the reserved ``event``/``ts``/``requestId`` keys are
    re-keyed with a trailing underscore instead of overwriting them
    (the positional-only ``/`` keeps a caller's ``event=...`` out of
    the parameter slot, where it used to raise TypeError mid-log).
    """
    if not logger.isEnabledFor(level):
        return
    record = {"event": event, "ts": round(time.time(), 3)}
    rid = _request_id.get()
    if rid is not None:
        record["requestId"] = rid
    for key in _RESERVED_KEYS:
        if key in fields:
            fields[f"{key}_"] = fields.pop(key)
    record.update(fields)
    logger.log(level, json.dumps(record, default=str))
