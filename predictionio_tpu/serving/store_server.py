"""Store server — network service for the metadata + model repositories.

The reference reaches external metadata/model stores through server
processes it does not ship (elasticsearch for the seven metadata DAOs,
``data/.../storage/elasticsearch/ESApps.scala:1``; an HDFS namenode for
model blobs, ``.../hdfs/HDFSModels.scala:1``). This framework ships the
service itself: ``pio-tpu storeserver`` exposes any locally-configured
backend (sqlite + localfs by default) over JSON/HTTP so every other
process — trainer, event server, engine servers, dashboard — can point
its METADATA/MODELDATA repositories at one host via the ``httpstore``
backend type (:mod:`predictionio_tpu.data.storage.httpstore`, which
also defines the wire codecs used here).

Routes::

    GET    /                                    liveness + backing info
    POST   /meta/<kind>                         insert    -> {"id": ...}
    GET    /meta/<kind>                         list (query-param filters)
    GET    /meta/<kind>/<id>                    get       -> record | 404
    PUT    /meta/<kind>/<id>                    update    -> {"ok": bool}
    DELETE /meta/<kind>/<id>                    delete    -> {"ok": bool}
    GET/PUT/DELETE /meta/engine_manifests/<id>/<version>   (2-part key)
    GET    /models                              -> {"ids": [...]} | 501
    PUT    /models/<id>                         blob upload (octet-stream)
    GET    /models/<id>                         blob | 404
    DELETE /models/<id>                         -> {"ok": bool}
    PUT    /events/<app_id>                     init      -> {"ok": bool}
    DELETE /events/<app_id>                     remove    -> {"ok": bool}
    POST   /events/<app_id>                     insert    -> {"id": ...}
    POST   /events/<app_id>/batch               -> {"ids": [...]} | 409
    GET    /events/<app_id>                     find (query-param filters)
    GET    /events/<app_id>/watermark           event-set summary
    GET    /events/<app_id>/one/<event_id>      event | 404
    DELETE /events/<app_id>/one/<event_id>      -> {"ok": bool}

(``?channel_id=`` selects a channel on every /events route.) Event
inserts honor ``X-PIO-Store-Seq`` replay dedupe and the replicated
tier's peers join via ``--peer`` (docs/storage.md "Replication &
failover"): ``/healthz`` then reports replication role + per-peer lag
and failover/repair transitions land in ``/debug/timeline.json``.

Auth: optional — start with an access key (``--access-key`` or
``PIO_SERVER_ACCESS_KEY``) and every request must carry it
(``Authorization: Bearer <key>`` or ``?accessKey=``), the same
:class:`~predictionio_tpu.serving.config.ServerConfig` contract the
dashboard uses.
"""

from __future__ import annotations

import collections
import datetime as _dt
import hashlib
import json
import threading
import urllib.parse

from predictionio_tpu.data.event import Event, EventValidationError
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import (
    Model,
    PartialBatchError,
    StorageError,
)
from predictionio_tpu.data.storage.httpstore import (
    STORE_REPLAY_HEADER,
    STORE_SEQ_HEADER,
    TRI_NULL,
    access_key_from_json,
    access_key_to_json,
    app_from_json,
    app_to_json,
    channel_from_json,
    channel_to_json,
    engine_instance_from_json,
    engine_instance_to_json,
    evaluation_instance_from_json,
    evaluation_instance_to_json,
    manifest_from_json,
    manifest_to_json,
)
from predictionio_tpu.obs import MetricRegistry, get_registry
from predictionio_tpu.obs import timeline as timeline_mod
from predictionio_tpu.obs import tracing
from predictionio_tpu.serving.config import ServerConfig
from predictionio_tpu.serving.http import (
    HTTPError,
    HTTPServer,
    Request,
    Response,
    Router,
    install_metrics_routes,
)


def event_set_checksum(ids) -> str:
    """Order-independent digest of an event-id set: XOR-fold of each
    id's sha256 prefix. Two peers holding the same events report the
    same checksum regardless of insertion order — the cheap equality
    probe anti-entropy runs before deciding to stream a delta."""
    acc = 0
    n = 0
    for event_id in ids:
        digest = hashlib.sha256(event_id.encode()).digest()
        acc ^= int.from_bytes(digest[:8], "big")
        n += 1
    return f"{n}:{acc:016x}"


class EventWatermarkCache:
    """Incrementally-maintained per-(app, channel) event-set summary.

    Without it every anti-entropy round is O(total events) on BOTH
    sides — the peer's ``/events/<app>/watermark`` handler and the
    local comparison each stream the full log — and the steady-state
    sync cost grows without bound as the log grows. Here the full log
    is scanned once per coordinate (cold start), after which every
    insert folds into the running XOR checksum (the fold is its own
    inverse, so the digest stays order-independent and matches
    :func:`event_set_checksum` exactly).

    Synchronization rides the server's ingest lock, which already
    serializes every event-log mutation: :meth:`record_insert_locked`
    must be called WITH the lock held (it takes none itself — the lock
    is not reentrant); :meth:`summary` and :meth:`invalidate` acquire
    it. Deletes and log drops invalidate the coordinate — they are
    rare, and the next :meth:`summary` rescans once.
    """

    def __init__(self, ingest_lock: threading.Lock):
        self._lock = ingest_lock
        self._entries: dict[tuple[int, int | None], dict] = {}

    @staticmethod
    def _fold(event_id: str) -> int:
        digest = hashlib.sha256(event_id.encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def record_insert_locked(
        self, app_id: int, channel_id: int | None, event: Event
    ) -> None:
        """Fold one freshly-inserted event in. Caller holds the ingest
        lock (the same critical section as the DAO insert, so the scan
        in :meth:`summary` can never interleave and double-count)."""
        entry = self._entries.get((app_id, channel_id))
        if entry is None:
            return  # cold coordinate: the next summary() scan sees it
        entry["acc"] ^= self._fold(event.event_id)
        entry["count"] += 1
        ct = event.creation_time
        if ct is not None and (
            entry["latest"] is None or ct > entry["latest"]
        ):
            entry["latest"] = ct
            entry["latest_id"] = event.event_id

    def invalidate(self, app_id: int, channel_id: int | None) -> None:
        with self._lock:
            self._entries.pop((app_id, channel_id), None)

    def summary(self, app_id: int, channel_id: int | None, dao) -> dict:
        """The coordinate's summary: ``count``, ``checksum``,
        ``latest`` (creation-time datetime | None), ``latestId``.
        Rebuilds from a full scan only when the coordinate is cold or
        was invalidated."""
        with self._lock:
            entry = self._entries.get((app_id, channel_id))
            if entry is None:
                entry = {
                    "acc": 0, "count": 0, "latest": None, "latest_id": None
                }
                for e in dao.find(app_id, channel_id):
                    entry["acc"] ^= self._fold(e.event_id)
                    entry["count"] += 1
                    if (
                        entry["latest"] is None
                        or e.creation_time > entry["latest"]
                    ):
                        entry["latest"] = e.creation_time
                        entry["latest_id"] = e.event_id
                self._entries[(app_id, channel_id)] = entry
            return {
                "count": entry["count"],
                "checksum": f"{entry['count']}:{entry['acc']:016x}",
                "latest": entry["latest"],
                "latestId": entry["latest_id"],
            }


class StoreServer:
    """Key auth and TLS are server-level concerns: ``create_store_server``
    hands the :class:`ServerConfig` to :class:`HTTPServer`, which
    enforces the key on every route before dispatch."""

    def __init__(
        self,
        storage: Storage | None = None,
        registry: MetricRegistry | None = None,
        tracer: tracing.Tracer | None = None,
    ):
        self._storage = storage or get_storage()
        self.registry = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else tracing.get_tracer()
        self.timeline = timeline_mod.Timeline(registry=self.registry)
        timeline_mod.set_timeline(self.timeline)
        #: X-PIO-Store-Seq replay dedupe: writer -> (max_seq, window of
        #: seq -> (status, body)). A writer id is shared by every thread
        #: of one client process, so commits interleave: a torn seq-5
        #: retry can arrive after seq 6 committed, and a single
        #: last-seq slot would wave it through as "new". The window
        #: remembers recent responses per writer; anything at or below
        #: the high-water mark that misses the window falls back to the
        #: id-existence check. Bounded LRU on both axes so writer churn
        #: cannot grow it.
        self._seq_cache: collections.OrderedDict[
            str,
            tuple[int, collections.OrderedDict[int, tuple[int, object]]],
        ] = collections.OrderedDict()
        self._seq_lock = threading.Lock()
        #: serializes existence-check + append on the event routes with
        #: the anti-entropy pull — both are check-then-insert against an
        #: append-only log, and interleaving them lands duplicate
        #: records no repair pass can ever remove
        self.ingest_lock = threading.Lock()
        #: incremental per-(app, channel) watermark summaries, shared
        #: with the anti-entropy loop so steady-state sync rounds stay
        #: O(delta) instead of O(total events)
        self.watermarks = EventWatermarkCache(self.ingest_lock)
        #: set by create_store_server when --peer URLs are given; the
        #: /healthz payload and anti-entropy loop hang off it
        self.replication = None
        s = self._storage
        #: <kind> -> (dao getter, to_json, from_json, id parser);
        #: getters defer DAO construction to request time
        self._kinds = {
            "apps": (
                s.get_meta_data_apps, app_to_json, app_from_json, int
            ),
            "access_keys": (
                s.get_meta_data_access_keys,
                access_key_to_json,
                access_key_from_json,
                str,
            ),
            "channels": (
                s.get_meta_data_channels,
                channel_to_json,
                channel_from_json,
                int,
            ),
            "engine_instances": (
                s.get_meta_data_engine_instances,
                engine_instance_to_json,
                engine_instance_from_json,
                str,
            ),
            "evaluation_instances": (
                s.get_meta_data_evaluation_instances,
                evaluation_instance_to_json,
                evaluation_instance_from_json,
                str,
            ),
            "engine_manifests": (
                s.get_meta_data_engine_manifests,
                manifest_to_json,
                manifest_from_json,
                str,
            ),
        }
        self.router = Router()
        r = self.router
        install_metrics_routes(
            r, self.registry, self.tracer, timeline=self.timeline
        )
        r.healthz_extra = self._healthz_extra
        r.route("GET", "/", self._status)
        # events: fixed-tail routes before the parameterized ones so
        # ".../batch" and ".../watermark" never bind as an id
        r.route("POST", "/events/<app_id>/batch", self._event_batch)
        r.route("GET", "/events/<app_id>/watermark", self._event_watermark)
        r.route("GET", "/events/<app_id>/one/<event_id>", self._event_get)
        r.route("DELETE", "/events/<app_id>/one/<event_id>",
                self._event_delete)
        r.route("PUT", "/events/<app_id>", self._event_init)
        r.route("DELETE", "/events/<app_id>", self._event_remove)
        r.route("POST", "/events/<app_id>", self._event_insert)
        r.route("GET", "/events/<app_id>", self._event_find)
        r.route("GET", "/models", self._model_list)
        r.route("GET", "/meta/engine_manifests/<id>/<version>",
                self._manifest_get)
        r.route("PUT", "/meta/engine_manifests/<id>/<version>",
                self._manifest_update)
        r.route("DELETE", "/meta/engine_manifests/<id>/<version>",
                self._manifest_delete)
        for method, pattern, handler in (
            ("POST", "/meta/<kind>", self._insert),
            ("GET", "/meta/<kind>", self._list),
            ("GET", "/meta/<kind>/<id>", self._get),
            ("PUT", "/meta/<kind>/<id>", self._update),
            ("DELETE", "/meta/<kind>/<id>", self._delete),
        ):
            r.route(method, pattern, handler)
        r.route("PUT", "/models/<id>", self._model_put)
        r.route("GET", "/models/<id>", self._model_get)
        r.route("DELETE", "/models/<id>", self._model_delete)

    # -- plumbing ---------------------------------------------------------

    def _kind(self, request: Request):
        """Resolve <kind> → (dao, to_json, from_json, id-parser)."""
        kind = request.path_params["kind"]
        if kind not in self._kinds:
            raise HTTPError(404, f"unknown metadata kind {kind!r}")
        getter, to_json, from_json, id_parse = self._kinds[kind]
        try:
            dao = getter()
        except StorageError as e:
            raise HTTPError(500, str(e)) from e
        return kind, dao, to_json, from_json, id_parse

    @staticmethod
    def _parse_id(id_parse, raw: str):
        try:
            return id_parse(urllib.parse.unquote(raw))
        except ValueError as e:
            raise HTTPError(400, f"bad id {raw!r}") from e

    @staticmethod
    def _reject_manifest_single_key(kind: str) -> None:
        """Engine manifests are keyed by (id, version); the single-id
        routes would call their DAO with the wrong arity."""
        if kind == "engine_manifests":
            raise HTTPError(
                400,
                "engine_manifests is keyed by (id, version); use "
                "/meta/engine_manifests/<id>/<version>",
            )

    def _healthz_extra(self) -> dict:
        if self.replication is None:
            return {}
        return {"replication": self.replication.status()}

    # -- X-PIO-Store-Seq replay dedupe ------------------------------------

    @staticmethod
    def _parse_seq(raw: str) -> tuple[str, int] | None:
        writer, sep, seq = raw.rpartition(":")
        if not sep or not writer:
            return None
        try:
            return writer, int(seq)
        except ValueError:
            return None

    _SEQ_CACHE_MAX = 1024  # writers remembered
    _SEQ_WINDOW = 128  # responses remembered per writer

    def _seq_replay(self, request: Request):
        """Returns (token, cached Response | None, writer_known). A
        replay of a recently-committed sequence answers from the
        per-writer response window without touching the backend — the
        append-only eventlog would otherwise record the event twice.
        ``writer_known=False`` tells the insert path to fall back to an
        id-existence check; it is forced whenever the fast path cannot
        PROVE first contact:

        * cold cache — first write from this writer since the server
          started;
        * ``seq <= max_seq`` but outside the response window — the
          writer id is shared across client threads, so a torn seq-5
          retry can arrive after seq 6 committed (or after its own
          slot was evicted) and must not skip the id check;
        * ``X-PIO-Store-Replay`` — hinted-handoff replays arrive AFTER
          anti-entropy may have pulled the same events from a sibling,
          so even a fresh seq proves nothing for them.

        Only ``seq > max_seq`` without the replay marker (a send this
        server provably never committed) takes the fast path."""
        replay = bool(request.headers.get(STORE_REPLAY_HEADER))
        raw = (request.headers.get(STORE_SEQ_HEADER) or "").strip()
        if not raw:
            return None, None, not replay
        token = self._parse_seq(raw)
        if token is None:
            raise HTTPError(
                400, f"bad {STORE_SEQ_HEADER} {raw!r}; want <writer>:<seq>"
            )
        writer, seq = token
        with self._seq_lock:
            hit = self._seq_cache.get(writer)
            if hit is not None:
                self._seq_cache.move_to_end(writer)
                max_seq, window = hit
                slot = window.get(seq)
                if slot is not None:
                    status, body = slot
                    return token, Response(status, body), True
                if seq <= max_seq:
                    return token, None, False
                return token, None, not replay
        return token, None, False

    def _seq_commit(self, token, status: int, body) -> None:
        if token is None:
            return
        writer, seq = token
        with self._seq_lock:
            hit = self._seq_cache.get(writer)
            if hit is None:
                max_seq = seq
                window: collections.OrderedDict[int, tuple[int, object]] = (
                    collections.OrderedDict()
                )
            else:
                max_seq, window = hit
                max_seq = max(max_seq, seq)
            window[seq] = (status, body)
            window.move_to_end(seq)
            while len(window) > self._SEQ_WINDOW:
                window.popitem(last=False)
            self._seq_cache[writer] = (max_seq, window)
            self._seq_cache.move_to_end(writer)
            while len(self._seq_cache) > self._SEQ_CACHE_MAX:
                self._seq_cache.popitem(last=False)

    # -- routes -----------------------------------------------------------

    def _status(self, request: Request) -> Response:
        return Response(200, {"status": "alive", "service": "storeserver"})

    def _insert(self, request: Request) -> Response:
        kind, dao, _to_json, from_json, _ = self._kind(request)
        body = request.json()
        if not isinstance(body, dict):
            raise HTTPError(400, "record JSON object required")
        try:
            record = from_json(body)
        except (KeyError, TypeError, ValueError) as e:
            raise HTTPError(400, f"bad {kind} record: {e}") from e
        with tracing.span(f"dao/{kind}.insert"):
            out = dao.insert(record)
        # insert contracts differ by DAO: apps/channels → id|None on
        # conflict; access_keys → key|None; instances → id; manifests →
        # None (keyed by the record itself). Normalize to {"id": ...}.
        return Response(201, {"id": out})

    def _list(self, request: Request) -> Response:
        kind, dao, to_json, _f, _ = self._kind(request)
        q = request.query
        with tracing.span(f"dao/{kind}.list"):
            return self._list_inner(kind, dao, to_json, q)

    def _list_inner(self, kind, dao, to_json, q) -> Response:
        if kind == "apps" and "name" in q:
            app = dao.get_by_name(q["name"])
            return Response(200, [to_json(app)] if app else [])
        if kind in ("access_keys", "channels") and "app_id" in q:
            try:
                app_id = int(q["app_id"])
            except ValueError as e:
                raise HTTPError(400, "app_id must be an int") from e
            return Response(
                200, [to_json(r) for r in dao.get_by_app_id(app_id)]
            )
        if kind == "engine_instances" and q.get("completed"):
            key = (
                q.get("engine_id", ""),
                q.get("engine_version", ""),
                q.get("engine_variant", ""),
            )
            if q.get("latest") not in (None, "0"):
                latest = dao.get_latest_completed(*key)
                return Response(200, [to_json(latest)] if latest else [])
            return Response(
                200, [to_json(r) for r in dao.get_completed(*key)]
            )
        if kind == "evaluation_instances" and q.get("completed"):
            return Response(200, [to_json(r) for r in dao.get_completed()])
        return Response(200, [to_json(r) for r in dao.get_all()])

    def _get(self, request: Request) -> Response:
        kind, dao, to_json, _f, id_parse = self._kind(request)
        self._reject_manifest_single_key(kind)
        with tracing.span(f"dao/{kind}.get"):
            record = dao.get(
                self._parse_id(id_parse, request.path_params["id"])
            )
        if record is None:
            raise HTTPError(404, "not found")
        return Response(200, to_json(record))

    def _update(self, request: Request) -> Response:
        kind, dao, _t, from_json, _ = self._kind(request)
        self._reject_manifest_single_key(kind)
        body = request.json()
        if not isinstance(body, dict):
            raise HTTPError(400, "record JSON object required")
        try:
            record = from_json(body)
        except (KeyError, TypeError, ValueError) as e:
            raise HTTPError(400, f"bad {kind} record: {e}") from e
        with tracing.span(f"dao/{kind}.update"):
            return Response(200, {"ok": bool(dao.update(record))})

    def _delete(self, request: Request) -> Response:
        kind, dao, _t, _f, id_parse = self._kind(request)
        self._reject_manifest_single_key(kind)
        with tracing.span(f"dao/{kind}.delete"):
            ok = dao.delete(
                self._parse_id(id_parse, request.path_params["id"])
            )
        return Response(200, {"ok": bool(ok)})

    # -- engine manifests (two-part key) ----------------------------------

    def _manifests(self):
        try:
            return self._storage.get_meta_data_engine_manifests()
        except StorageError as e:
            raise HTTPError(500, str(e)) from e

    def _manifest_get(self, request: Request) -> Response:
        m = self._manifests().get(
            urllib.parse.unquote(request.path_params["id"]),
            urllib.parse.unquote(request.path_params["version"]),
        )
        if m is None:
            raise HTTPError(404, "not found")
        return Response(200, manifest_to_json(m))

    def _manifest_update(self, request: Request) -> Response:
        body = request.json()
        if not isinstance(body, dict):
            raise HTTPError(400, "record JSON object required")
        try:
            manifest = manifest_from_json(body)
        except (KeyError, TypeError, ValueError) as e:
            raise HTTPError(400, f"bad manifest record: {e}") from e
        upsert = request.query.get("upsert") not in (None, "0")
        try:
            self._manifests().update(manifest, upsert=upsert)
        except KeyError as e:
            # non-upsert update of a missing manifest: a contract error
            # the client re-raises as KeyError
            raise HTTPError(404, str(e)) from e
        return Response(200, {"ok": True})

    def _manifest_delete(self, request: Request) -> Response:
        ok = self._manifests().delete(
            urllib.parse.unquote(request.path_params["id"]),
            urllib.parse.unquote(request.path_params["version"]),
        )
        return Response(200, {"ok": bool(ok)})

    # -- model blobs ------------------------------------------------------

    def _models(self):
        try:
            return self._storage.get_model_data_models()
        except StorageError as e:
            raise HTTPError(500, str(e)) from e

    def _model_put(self, request: Request) -> Response:
        model_id = urllib.parse.unquote(request.path_params["id"])
        claimed = (request.headers.get("X-PIO-SHA256") or "").strip().lower()
        if claimed:
            # upload integrity (docs/training.md "Model generations"):
            # verify the digest over the bytes that actually arrived —
            # a transit flip or truncation is refused, never stored
            import hashlib

            actual = hashlib.sha256(request.body).hexdigest()
            if actual != claimed:
                raise HTTPError(
                    422,
                    f"model upload integrity failure: received sha256 "
                    f"{actual[:12]}… != claimed {claimed[:12]}…",
                )
        with tracing.span("dao/models.insert", bytes=len(request.body)):
            self._models().insert(Model(id=model_id, models=request.body))
        return Response(201, {"id": model_id})

    def _model_get(self, request: Request) -> Response:
        model_id = urllib.parse.unquote(request.path_params["id"])
        with tracing.span("dao/models.get"):
            model = self._models().get(model_id)
        if model is None:
            raise HTTPError(404, "not found")
        return Response(
            200, model.models, content_type="application/octet-stream"
        )

    def _model_delete(self, request: Request) -> Response:
        model_id = urllib.parse.unquote(request.path_params["id"])
        return Response(200, {"ok": bool(self._models().delete(model_id))})

    def _model_list(self, request: Request) -> Response:
        with tracing.span("dao/models.list_ids"):
            ids = self._models().list_ids()
        if ids is None:
            # backend without enumeration: anti-entropy skips the
            # model-repair pass rather than failing the peer
            raise HTTPError(501, "model backend cannot enumerate ids")
        return Response(200, {"ids": ids})

    # -- events -----------------------------------------------------------

    def _events(self):
        try:
            return self._storage.get_events()
        except StorageError as e:
            raise HTTPError(500, str(e)) from e

    @staticmethod
    def _event_coords(request: Request) -> tuple[int, int | None]:
        try:
            app_id = int(request.path_params["app_id"])
        except ValueError as e:
            raise HTTPError(400, "app_id must be an int") from e
        chan_raw = request.query.get("channel_id")
        if chan_raw in (None, ""):
            return app_id, None
        try:
            return app_id, int(chan_raw)
        except ValueError as e:
            raise HTTPError(400, "channel_id must be an int") from e

    def _event_init(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        with tracing.span("dao/events.init"):
            ok = self._events().init(app_id, channel_id)
        self.watermarks.invalidate(app_id, channel_id)
        return Response(200, {"ok": bool(ok)})

    def _event_remove(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        with tracing.span("dao/events.remove"):
            ok = self._events().remove(app_id, channel_id)
        self.watermarks.invalidate(app_id, channel_id)
        return Response(200, {"ok": bool(ok)})

    @staticmethod
    def _parse_event(d) -> Event:
        if not isinstance(d, dict):
            raise HTTPError(400, "event JSON object required")
        try:
            # stamp missing ids HERE so the response (and the seq
            # cache) can report concrete ids the client may replay
            return Event.from_json_dict(d).with_id(d.get("eventId"))
        except EventValidationError as e:
            raise HTTPError(400, f"bad event: {e}") from e

    def _event_insert(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        token, cached, writer_known = self._seq_replay(request)
        if cached is not None:
            return cached
        event = self._parse_event(request.json())
        dao = self._events()
        with self.ingest_lock:
            if not writer_known and dao.get(
                event.event_id, app_id, channel_id
            ) is not None:
                # cold-cache replay (writer's first contact since this
                # server started): the id is already durable here
                self._seq_commit(token, 201, {"id": event.event_id})
                return Response(201, {"id": event.event_id})
            with tracing.span("dao/events.insert"):
                event_id = dao.insert(event, app_id, channel_id)
            self.watermarks.record_insert_locked(app_id, channel_id, event)
        self._seq_commit(token, 201, {"id": event_id})
        return Response(201, {"id": event_id})

    def _event_batch(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        token, cached, writer_known = self._seq_replay(request)
        if cached is not None:
            return cached
        body = request.json()
        if not isinstance(body, list):
            raise HTTPError(400, "event JSON array required")
        events = [self._parse_event(d) for d in body]
        all_ids = [e.event_id for e in events]
        dao = self._events()
        try:
            with self.ingest_lock:
                if not writer_known:
                    # cold-cache replay window: skip events already
                    # durable so the append-only eventlog never records
                    # one twice (the response still acks the FULL batch
                    # — they are all here)
                    events = [
                        e
                        for e in events
                        if dao.get(e.event_id, app_id, channel_id) is None
                    ]
                with tracing.span(
                    "dao/events.insert_batch", n=len(events)
                ):
                    if events:
                        dao.insert_batch(events, app_id, channel_id)
                for ev in events:
                    self.watermarks.record_insert_locked(
                        app_id, channel_id, ev
                    )
        except PartialBatchError as e:
            # an unknown prefix of the batch landed: rescan on the
            # next watermark read rather than guess
            self.watermarks.invalidate(app_id, channel_id)
            # durable-prefix report on 409: a 5xx would be consumed by
            # the client transport before the prefix could be read.
            # Ids skipped as already-durable count as inserted.
            remaining = {ev.event_id for ev in events}
            durable = [i for i in all_ids if i not in remaining]
            durable.extend(e.inserted_ids)
            payload = {"error": str(e), "insertedIds": durable}
            self._seq_commit(token, 409, payload)
            return Response(409, payload)
        self._seq_commit(token, 201, {"ids": all_ids})
        return Response(201, {"ids": all_ids})

    def _event_find(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        q = request.query

        def _time(key: str) -> _dt.datetime | None:
            raw = q.get(key)
            if raw in (None, ""):
                return None
            try:
                return _dt.datetime.fromisoformat(raw)
            except ValueError as e:
                raise HTTPError(400, f"{key} not ISO-8601: {raw!r}") from e

        def _tri(key: str):
            raw = q.get(key)
            if raw is None:
                return ...
            return None if raw == TRI_NULL else raw

        event_names = None
        if q.get("event_names") not in (None, ""):
            try:
                event_names = json.loads(q["event_names"])
            except ValueError as e:
                raise HTTPError(
                    400, "event_names must be a JSON array"
                ) from e
        limit = None
        if q.get("limit") not in (None, ""):
            try:
                limit = int(q["limit"])
            except ValueError as e:
                raise HTTPError(400, "limit must be an int") from e
        with tracing.span("dao/events.find"):
            out = [
                e.to_json_dict()
                for e in self._events().find(
                    app_id,
                    channel_id,
                    start_time=_time("start_time"),
                    until_time=_time("until_time"),
                    entity_type=q.get("entity_type"),
                    entity_id=q.get("entity_id"),
                    event_names=event_names,
                    target_entity_type=_tri("target_entity_type"),
                    target_entity_id=_tri("target_entity_id"),
                    limit=limit,
                    reversed=q.get("reversed") not in (None, "", "0"),
                )
            ]
        return Response(200, out)

    def _event_watermark(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        with tracing.span("dao/events.watermark"):
            summary = self.watermarks.summary(
                app_id, channel_id, self._events()
            )
        latest = summary["latest"]
        summary["latest"] = latest.isoformat() if latest else None
        return Response(200, summary)

    def _event_get(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        event_id = urllib.parse.unquote(request.path_params["event_id"])
        with tracing.span("dao/events.get"):
            event = self._events().get(event_id, app_id, channel_id)
        if event is None:
            raise HTTPError(404, "not found")
        return Response(200, event.to_json_dict())

    def _event_delete(self, request: Request) -> Response:
        app_id, channel_id = self._event_coords(request)
        event_id = urllib.parse.unquote(request.path_params["event_id"])
        with tracing.span("dao/events.delete"):
            ok = self._events().delete(event_id, app_id, channel_id)
        if ok:
            self.watermarks.invalidate(app_id, channel_id)
        return Response(200, {"ok": bool(ok)})


def create_store_server(
    host: str = "0.0.0.0",
    port: int = 7072,
    storage: Storage | None = None,
    server_config: ServerConfig | None = None,
    registry: MetricRegistry | None = None,
    tracer: tracing.Tracer | None = None,
    peers: list[str] | None = None,
    role: str = "replica",
) -> HTTPServer:
    """``peers`` (replica-set siblings, base URLs) turns on the
    anti-entropy loop: this node periodically compares event watermarks
    + model sets + metadata against each peer and pulls what it is
    missing, so a restarted node converges without operator action
    (docs/storage.md "Replication & failover"). ``role`` is reporting
    only — every node repairs itself; quorum placement is the client's
    job (data/storage/replicated.py)."""
    server = StoreServer(storage, registry=registry, tracer=tracer)
    http = HTTPServer(
        server.router,
        host=host,
        port=port,
        server_config=server_config,
        service="storeserver",
        registry=server.registry,
        tracer=server.tracer,
    )
    if peers:
        from predictionio_tpu.data.storage.replicated import AntiEntropyLoop

        loop = AntiEntropyLoop(
            storage=server._storage,
            peers=peers,
            role=role,
            registry=server.registry,
            timeline=server.timeline,
            key=(server_config.access_key if server_config else "") or None,
            insert_lock=server.ingest_lock,
            watermarks=server.watermarks,
        )
        server.replication = loop
        loop.start()
        http.add_drain_hook(loop.close)
    #: the app object, reachable from the HTTPServer handle (tests and
    #: the CLI reuse it for replication status)
    http.store_app = server
    return http
