"""Vendored pure-Python MySQL driver (client/server protocol 4.1, DB-API 2.0).

The reference reaches MySQL through a JDBC driver jar on the classpath
(``data/.../storage/jdbc/JDBCUtils.scala:26-46`` — ``driverType`` picks
the mysql Driver); the Python analogue would be "pip install pymysql",
which this environment cannot do. Like
:mod:`~predictionio_tpu.data.storage.pgwire` for PostgreSQL, this module
removes the dependency: a minimal DB-API driver speaking the MySQL
client/server protocol over a plain socket, implementing exactly what
:mod:`~predictionio_tpu.data.storage.sql_common` +
:class:`~predictionio_tpu.data.storage.mysql.MySQLDialect` need:

* handshake v10 + ``mysql_native_password`` auth (incl. the
  AuthSwitchRequest path a real server takes when its default is
  ``caching_sha2_password``)
* ``COM_QUERY`` with the text protocol and client-side parameter
  interpolation (``format``/``%s`` paramstyle, like pymysql)
* text-format result decoding by column type / charset
* explicit transactions (lazy BEGIN; ``commit``/``rollback``)
* the DB-API exception hierarchy mapped from server error codes

Not implemented (not needed here): prepared statements (binary
protocol), compression, TLS, ``caching_sha2_password`` itself,
multi-statement/multi-resultset.

Wire-format ground truth lives in ``tests/test_mywire_golden.py`` —
spec-derived byte frames asserted against this driver and the
:mod:`~predictionio_tpu.data.storage.minimysql` server independently.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, Iterable, Sequence

apilevel = "2.0"
threadsafety = 1  # module-level sharing only; one connection per thread
paramstyle = "format"

# -- capability flags (protocol constants) ----------------------------------
CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_TRANSACTIONS = 0x00002000
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000

#: what this driver speaks (CONNECT_WITH_DB added when a db is named)
BASE_CAPABILITIES = (
    CLIENT_LONG_PASSWORD
    | CLIENT_PROTOCOL_41
    | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION
    | CLIENT_PLUGIN_AUTH
)

COM_QUIT = 0x01
COM_QUERY = 0x03
COM_PING = 0x0E

#: sanity ceiling on one protocol packet payload (the wire maximum)
_MAX_PACKET = 0xFFFFFF

# column type codes (text protocol decode)
_INT_TYPES = {1, 2, 3, 8, 9, 13}  # TINY/SHORT/LONG/LONGLONG/INT24/YEAR
_FLOAT_TYPES = {0, 4, 5, 246}  # DECIMAL/FLOAT/DOUBLE/NEWDECIMAL
_BLOB_TYPES = {249, 250, 251, 252}  # TINY/MEDIUM/LONG/BLOB
_BINARY_CHARSET = 63


# -- DB-API exceptions ------------------------------------------------------


class Error(Exception):
    """Base DB-API error; carries the server errno when known."""

    def __init__(self, msg: str, errno: int | None = None):
        super().__init__(msg)
        self.errno = errno


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


Warning = type("Warning", (Exception,), {})  # noqa: A001 - DB-API name
DataError = type("DataError", (DatabaseError,), {})

#: duplicate-key family → IntegrityError
_INTEGRITY_ERRNOS = {1022, 1062, 1169, 1557, 1586, 1761, 1762, 1859}
#: syntax / unknown object family → ProgrammingError (pymysql parity:
#: 1146 no-such-table is a ProgrammingError there too)
_PROGRAMMING_ERRNOS = {1054, 1061, 1064, 1103, 1146, 1148}


def _error_for(errno: int, msg: str) -> DatabaseError:
    text = f"({errno}, {msg!r})"
    if errno in _INTEGRITY_ERRNOS:
        return IntegrityError(text, errno)
    if errno in _PROGRAMMING_ERRNOS:
        return ProgrammingError(text, errno)
    return OperationalError(text, errno)


# -- mysql_native_password scramble -----------------------------------------


def native_password_scramble(password: str, salt: bytes) -> bytes:
    """``SHA1(password) XOR SHA1(salt + SHA1(SHA1(password)))`` — the
    documented mysql_native_password response (empty password → empty
    response)."""
    if not password:
        return b""
    pw = password.encode("utf-8")
    h1 = hashlib.sha1(pw).digest()
    h2 = hashlib.sha1(h1).digest()
    mask = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, mask))


# -- literal quoting (client-side interpolation, %s paramstyle) -------------


def quote(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        # hex literal: identical meaning in MySQL and sqlite (minimysql)
        return f"x'{bytes(value).hex()}'"
    if isinstance(value, str):
        # backslash is an escape character in MySQL's default sql_mode;
        # doubling the quote is understood in every mode. NUL must be
        # escaped (raw 0x00 ends the statement for most servers; note
        # the sqlite-backed minimysql cannot store NUL either way)
        return "'" + (
            value.replace("\\", "\\\\")
            .replace("\x00", "\\0")
            .replace("'", "''")
        ) + "'"
    raise ProgrammingError(f"cannot adapt parameter of type {type(value)}")


def interpolate(sql: str, params: Sequence[Any]) -> str:
    if not params:
        return sql
    parts = sql.split("%s")
    if len(parts) != len(params) + 1:
        raise ProgrammingError(
            f"statement has {len(parts) - 1} placeholders but "
            f"{len(params)} parameters were supplied"
        )
    out = [parts[0]]
    for part, p in zip(parts[1:], params):
        out.append(quote(p))
        out.append(part)
    return "".join(out)


# -- length-encoded primitives ----------------------------------------------


def lenenc_int(value: int) -> bytes:
    if value < 0xFB:
        return bytes([value])
    if value < 1 << 16:
        return b"\xfc" + struct.pack("<H", value)
    if value < 1 << 24:
        return b"\xfd" + struct.pack("<I", value)[:3]
    return b"\xfe" + struct.pack("<Q", value)


def read_lenenc_int(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return (
            struct.unpack_from("<I", buf[pos + 1:pos + 4] + b"\x00")[0],
            pos + 4,
        )
    if first == 0xFE:
        return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9
    raise InterfaceError(f"invalid length-encoded integer 0x{first:02x}")


def read_lenenc_bytes(buf: bytes, pos: int) -> tuple[bytes | None, int]:
    if buf[pos] == 0xFB:  # NULL marker (text resultset rows)
        return None, pos + 1
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


# -- packet plumbing --------------------------------------------------------


class _Packets:
    """Framed reads/writes: 3-byte LE length + 1-byte sequence id."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""
        self.seq = 0

    def send(self, payload: bytes) -> None:
        # payloads >= 16 MiB - 1 are split: each full 0xFFFFFF chunk is
        # followed by more, terminated by a short (possibly empty) chunk
        out = []
        offset = 0
        while True:
            chunk = payload[offset:offset + _MAX_PACKET]
            out.append(
                struct.pack("<I", len(chunk))[:3]
                + bytes([self.seq])
                + chunk
            )
            self.seq = (self.seq + 1) & 0xFF
            offset += len(chunk)
            if len(chunk) < _MAX_PACKET:
                break
        self._sock.sendall(b"".join(out))

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise OperationalError("server closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv(self) -> bytes:
        # reassemble split packets: a 0xFFFFFF-length packet continues
        # in the next one, until a short (possibly empty) packet ends it
        parts = []
        while True:
            header = self._read_exact(4)
            length = header[0] | header[1] << 8 | header[2] << 16
            self.seq = (header[3] + 1) & 0xFF
            parts.append(self._read_exact(length))
            if length < _MAX_PACKET:
                return b"".join(parts)


def _parse_err(payload: bytes) -> DatabaseError:
    # 0xff, errno (2 LE), '#' marker, 5-byte sqlstate, message
    (errno,) = struct.unpack_from("<H", payload, 1)
    rest = payload[3:]
    if rest[:1] == b"#":
        rest = rest[6:]  # skip marker + sqlstate
    return _error_for(errno, rest.decode("utf-8", "replace"))


def _parse_ok(payload: bytes) -> tuple[int, int]:
    """OK packet → (affected_rows, last_insert_id)."""
    pos = 1
    affected, pos = read_lenenc_int(payload, pos)
    last_id, pos = read_lenenc_int(payload, pos)
    return affected, last_id


def _is_eof(payload: bytes) -> bool:
    return payload[:1] == b"\xfe" and len(payload) < 9


# -- connection -------------------------------------------------------------


class Connection:
    def __init__(
        self,
        host: str = "localhost",
        port: int = 3306,
        database: str = "",
        user: str = "root",
        password: str = "",
        connect_timeout: float = 10.0,
    ):
        self._closed = False
        self._in_tx = False
        try:
            sock = socket.create_connection(
                (host, port), timeout=connect_timeout
            )
        except OSError as exc:
            self._closed = True
            raise OperationalError(
                f"cannot connect to {host}:{port}: {exc}"
            ) from exc
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._packets = _Packets(sock)
        self._sock = sock
        try:
            self._handshake(database, user, password)
        except BaseException:
            self.close()
            raise

    # -- session startup ---------------------------------------------------
    def _handshake(self, database: str, user: str, password: str) -> None:
        greeting = self._packets.recv()
        if greeting[:1] == b"\xff":
            raise _parse_err(greeting)
        if greeting[0] != 10:
            raise NotSupportedError(
                f"unsupported handshake protocol {greeting[0]}"
            )
        pos = greeting.index(b"\x00", 1) + 1  # server version string
        pos += 4  # connection id
        salt = greeting[pos:pos + 8]
        pos += 8 + 1  # auth-data part 1 + filler
        (cap_low,) = struct.unpack_from("<H", greeting, pos)
        pos += 2
        capabilities = cap_low
        plugin = "mysql_native_password"
        if pos < len(greeting):
            pos += 1 + 2  # charset, status
            (cap_high,) = struct.unpack_from("<H", greeting, pos)
            capabilities |= cap_high << 16
            pos += 2
            auth_len = greeting[pos]
            pos += 1 + 10  # auth data length + reserved
            if capabilities & CLIENT_SECURE_CONNECTION:
                take = max(13, auth_len - 8)
                salt += greeting[pos:pos + take].rstrip(b"\x00")[:12]
                pos += take
            if capabilities & CLIENT_PLUGIN_AUTH:
                end = greeting.index(b"\x00", pos)
                plugin = greeting[pos:end].decode("ascii")
        if not capabilities & CLIENT_PROTOCOL_41:
            raise NotSupportedError("server does not speak protocol 4.1")
        if plugin != "mysql_native_password":
            # respond with native anyway; servers defaulting to
            # caching_sha2 answer with an AuthSwitchRequest we honor
            plugin = "mysql_native_password"
        auth = native_password_scramble(password, salt)
        caps = BASE_CAPABILITIES | (
            CLIENT_CONNECT_WITH_DB if database else 0
        )
        response = (
            struct.pack("<I", caps)
            + struct.pack("<I", _MAX_PACKET)
            + bytes([33])  # utf8_general_ci
            + b"\x00" * 23
            + user.encode("utf-8") + b"\x00"
            + bytes([len(auth)]) + auth
        )
        if database:
            response += database.encode("utf-8") + b"\x00"
        response += b"mysql_native_password\x00"
        self._packets.send(response)
        reply = self._packets.recv()
        if reply[:1] == b"\xfe" and len(reply) > 1:
            # AuthSwitchRequest: plugin name NUL, then fresh salt
            end = reply.index(b"\x00", 1)
            new_plugin = reply[1:end].decode("ascii")
            if new_plugin != "mysql_native_password":
                raise NotSupportedError(
                    f"server requires unsupported auth plugin "
                    f"{new_plugin!r}"
                )
            new_salt = reply[end + 1:].rstrip(b"\x00")
            self._packets.send(
                native_password_scramble(password, new_salt)
            )
            reply = self._packets.recv()
        if reply[:1] == b"\xff":
            raise _parse_err(reply)
        if reply[:1] not in (b"\x00", b"\xfe"):
            raise InterfaceError("unexpected authentication reply")

    # -- query execution ---------------------------------------------------
    def _query(self, sql: str) -> tuple[list, list, int, int]:
        """Run one COM_QUERY; returns (columns, rows, rowcount, lastrowid).

        ``columns`` is ``[(name, type, charset), ...]`` for resultsets,
        ``[]`` for DML.
        """
        if self._closed:
            raise InterfaceError("connection is closed")
        self._packets.seq = 0
        self._packets.send(bytes([COM_QUERY]) + sql.encode("utf-8"))
        first = self._packets.recv()
        if first[:1] == b"\xff":
            raise _parse_err(first)
        if first[:1] == b"\x00":  # OK: DML, no resultset
            affected, last_id = _parse_ok(first)
            return [], [], affected, last_id
        ncols, _ = read_lenenc_int(first, 0)
        columns: list[tuple[str, int, int]] = []
        for _ in range(ncols):
            columns.append(self._parse_column(self._packets.recv()))
        eof = self._packets.recv()
        if not _is_eof(eof):
            raise InterfaceError("expected EOF after column definitions")
        rows: list[tuple] = []
        while True:
            payload = self._packets.recv()
            if _is_eof(payload):
                return columns, rows, len(rows), 0
            if payload[:1] == b"\xff":
                raise _parse_err(payload)
            pos, vals = 0, []
            for _name, ctype, charset in columns:
                raw, pos = read_lenenc_bytes(payload, pos)
                vals.append(self._decode(raw, ctype, charset))
            rows.append(tuple(vals))

    @staticmethod
    def _parse_column(payload: bytes) -> tuple[str, int, int]:
        pos = 0
        for _ in range(4):  # catalog, schema, table, org_table
            _skip, pos = read_lenenc_bytes(payload, pos)
        name, pos = read_lenenc_bytes(payload, pos)
        _org, pos = read_lenenc_bytes(payload, pos)
        pos += 1  # lenenc length of the fixed fields (0x0c)
        (charset,) = struct.unpack_from("<H", payload, pos)
        pos += 2 + 4  # charset + column length
        ctype = payload[pos]
        return (name or b"").decode("utf-8"), ctype, charset

    @staticmethod
    def _decode(raw: bytes | None, ctype: int, charset: int) -> Any:
        if raw is None:
            return None
        if ctype in _INT_TYPES:
            return int(raw)
        if ctype in _FLOAT_TYPES:
            return float(raw)
        if ctype in _BLOB_TYPES and charset == _BINARY_CHARSET:
            return raw
        return raw.decode("utf-8")

    def _exec_tx(self, sql: str) -> tuple[list, list, int, int]:
        if not self._in_tx:
            self._query("BEGIN")
            self._in_tx = True
        return self._query(sql)

    # -- DB-API surface ----------------------------------------------------
    def cursor(self) -> "Cursor":
        return Cursor(self)

    def commit(self) -> None:
        if self._in_tx:
            self._query("COMMIT")
            self._in_tx = False

    def rollback(self) -> None:
        if self._in_tx:
            try:
                self._query("ROLLBACK")
            finally:
                self._in_tx = False

    def ping(self) -> None:
        self._packets.seq = 0
        self._packets.send(bytes([COM_PING]))
        reply = self._packets.recv()
        if reply[:1] != b"\x00":
            raise OperationalError("ping failed")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._packets.seq = 0
                self._packets.send(bytes([COM_QUIT]))
            except (OSError, Error):
                pass
            self._sock.close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self._conn = conn
        self.description: list | None = None
        self.rowcount = -1
        self.lastrowid = 0
        self._rows: list[tuple] = []
        self._idx = 0

    def execute(self, sql: str, params: Sequence[Any] = ()) -> "Cursor":
        columns, rows, rowcount, lastrowid = self._conn._exec_tx(
            interpolate(sql, tuple(params))
        )
        self.description = (
            [
                (name, ctype, None, None, None, None, None)
                for name, ctype, _cs in columns
            ]
            or None
        )
        self._rows, self._idx = rows, 0
        self.rowcount, self.lastrowid = rowcount, lastrowid
        return self

    def executemany(
        self, sql: str, seq_of_params: Iterable[Sequence[Any]]
    ) -> "Cursor":
        total = 0
        for params in seq_of_params:
            self.execute(sql, params)
            if self.rowcount > 0:
                total += self.rowcount
        self.description = None
        self._rows, self._idx = [], 0
        self.rowcount = total
        return self

    def fetchone(self):
        if self._idx >= len(self._rows):
            return None
        row = self._rows[self._idx]
        self._idx += 1
        return row

    def fetchmany(self, size: int | None = None):
        size = size or self.arraysize
        out = self._rows[self._idx:self._idx + size]
        self._idx += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._idx:]
        self._idx = len(self._rows)
        return out

    def close(self) -> None:
        self._rows = []

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row


def connect(**kwargs) -> Connection:
    return Connection(**kwargs)
