"""Thread-safe counters, gauges, and fixed-bucket latency histograms.

Design constraints, in priority order:

* **allocation-light on the hot path** — ``observe()``/``inc()`` on a
  bound (already-labeled) metric is a lock, an index, an add. Label
  resolution (``labels(...)``) allocates once and is meant to be done
  at wiring time, not per request.
* **fixed buckets** — histograms never grow; percentiles (p50/p95/p99)
  are derived at scrape time by linear interpolation inside the
  containing bucket, the standard Prometheus-client approach.
* **one registry, many feeders** — training loops and every server in
  the process share :func:`get_registry` so train-time and serve-time
  telemetry are one scrape; tests build private registries.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
from typing import Callable, Iterable

#: default latency buckets (seconds): sub-ms through 10 s, roughly
#: log-spaced — covers HTTP-tier microseconds and cold-compile spikes
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: batch-size buckets: powers of two, matching the micro-batcher's
#: compile buckets so occupancy reads directly as "which program ran"
OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: training-step buckets (seconds): steps span sub-second solves to
#: multi-hour epochs; the serving LATENCY_BUCKETS top out at 10 s and
#: would clamp every long step's derived percentiles to 10.0
TRAIN_STEP_BUCKETS = (
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0,
    300.0, 900.0, 3600.0, 14400.0,
)


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers without the trailing .0."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _label_str(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    pairs = ",".join(
        f'{n}="{_escape(v)}"' for n, v in zip(names, values)
    )
    return "{" + pairs + "}"


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _Metric:
    """Base: a named family holding one child per label-value tuple."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def labels(self, *values, **kv):
        """Bound child for a label-value combination — resolve once at
        wiring time, then hit the child on the hot path."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name")
            values = tuple(kv[n] for n in self.label_names)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
            return child

    def _make_child(self):
        raise NotImplementedError

    def _ensure_default(self):
        """Unlabeled metrics expose the family itself as the single
        child, so ``counter.inc()`` works without ``labels()``."""
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; "
                "call .labels(...) first"
            )
        return self.labels()

    def samples(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._ensure_default().inc(amount)

    @property
    def value(self) -> float:
        return self._ensure_default().value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate ``fn`` at scrape time (queue depths, pool sizes)."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 - a scrape must not 500
                return float("nan")
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._ensure_default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._ensure_default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._ensure_default().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._ensure_default().set_function(fn)

    @property
    def value(self) -> float:
        return self._ensure_default().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def time(self):
        """``with histogram.time():`` — observe the block's wall clock."""
        return _Timer(self)

    def percentile(self, q: float) -> float:
        """Derived quantile (0 < q < 1): linear interpolation inside
        the containing bucket, Prometheus ``histogram_quantile`` style.
        Returns NaN with no observations; the top bound for the +Inf
        bucket (nothing finer is knowable)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        return _quantile(self._bounds, counts, total, q)

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        # percentiles derive from the SAME copied counts — computing
        # them from live state could disagree with count/buckets when
        # a scrape races an observe()
        buckets = {_fmt(b): c for b, c in zip(self._bounds, counts)}
        # the overflow bucket travels explicitly so two snapshots can
        # be merged bucket-wise (fleet federation) without deriving it
        # as count - sum(buckets) — backward-compatible: finite-bound
        # readers (render_prometheus) never look the key up
        buckets["+Inf"] = counts[len(self._bounds)]
        return {
            "count": total,
            "sum": round(s, 6),
            "buckets": buckets,
            "p50": _nan_none(_quantile(self._bounds, counts, total, 0.50)),
            "p95": _nan_none(_quantile(self._bounds, counts, total, 0.95)),
            "p99": _nan_none(_quantile(self._bounds, counts, total, 0.99)),
        }


def _quantile(
    bounds: tuple[float, ...], counts: list[int], total: int, q: float
) -> float:
    if total == 0:
        return float("nan")
    rank = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            if i >= len(bounds):
                return bounds[-1] if bounds else float("nan")
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - (seen - c)) / c if c else 0.0
            return lo + (hi - lo) * frac
    return bounds[-1] if bounds else float("nan")


def _nan_none(v: float) -> float | None:
    return None if math.isnan(v) else round(v, 6)


class _Timer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._child.observe(time.perf_counter() - self._t0)
        return False


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Iterable[str] = (),
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._ensure_default().observe(value)

    def time(self):
        return self._ensure_default().time()

    def percentile(self, q: float) -> float:
        return self._ensure_default().percentile(q)


class MetricRegistry:
    """Get-or-create metric families; render Prometheus text or JSON."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, label_names, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                    existing.label_names != tuple(label_names)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels "
                        f"{existing.label_names}"
                    )
                return existing
            metric = cls(name, help, label_names, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", label_names: Iterable[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, tuple(label_names))

    def gauge(
        self, name: str, help: str = "", label_names: Iterable[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, tuple(label_names))

    def histogram(
        self,
        name: str,
        help: str = "",
        label_names: Iterable[str] = (),
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, tuple(label_names), buckets=buckets
        )

    # -- export -----------------------------------------------------------

    def _families(self) -> list[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for metric in self._families():
            lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            for values, child in metric.samples():
                label = _label_str(metric.label_names, values)
                if isinstance(child, _HistogramChild):
                    cumulative = 0
                    # render from ONE snapshot: mixing live counts with
                    # it would let a concurrent observe() make the
                    # cumulative buckets disagree with _count
                    snap = child.snapshot()
                    for bound in metric.buckets:
                        cumulative += snap["buckets"][_fmt(bound)]
                        le = _label_str(
                            metric.label_names + ("le",),
                            values + (_fmt(bound),),
                        )
                        lines.append(
                            f"{metric.name}_bucket{le} {cumulative}"
                        )
                    le = _label_str(
                        metric.label_names + ("le",), values + ("+Inf",)
                    )
                    lines.append(
                        f"{metric.name}_bucket{le} {snap['count']}"
                    )
                    lines.append(
                        f"{metric.name}_sum{label} {_fmt(snap['sum'])}"
                    )
                    lines.append(
                        f"{metric.name}_count{label} {snap['count']}"
                    )
                else:
                    lines.append(
                        f"{metric.name}{label} {_fmt(child.value)}"
                    )
        return "\n".join(lines) + "\n"

    def to_dict(self) -> dict:
        """JSON form: per family → per label-set → value/snapshot."""
        out: dict = {}
        for metric in self._families():
            entries = []
            for values, child in metric.samples():
                labels = dict(zip(metric.label_names, values))
                if isinstance(child, _HistogramChild):
                    entry = {"labels": labels, **child.snapshot()}
                else:
                    value = child.value
                    entry = {
                        "labels": labels,
                        "value": None if (
                            isinstance(value, float) and math.isnan(value)
                        ) else value,
                    }
                entries.append(entry)
            out[metric.name] = {
                "type": metric.kind,
                "help": metric.help,
                "samples": entries,
            }
        return out


#: telemetry-import wall clock — the uptime anchor for scrapes. (A
#: /proc/self/stat read would be a few ms more precise but platform-
#: bound; servers import telemetry within moments of process start.)
#: Exempt from the wall-clock lint rule: Prometheus defines
#: process_start_time_seconds as a unix epoch — scrapers compute
#: uptime as time() - this on THEIR clock, so a monotonic value here
#: would be meaningless off-host.
_PROCESS_START_TIME = time.time()  # pio-lint: disable=wall-clock -- Prometheus semantics: epoch, consumed off-host


def _read_resident_bytes() -> float:
    """RSS from ``/proc/self/statm`` (field 2, in pages)."""
    with open("/proc/self/statm", "rb") as f:
        pages = int(f.read().split()[1])
    return float(pages * os.sysconf("SC_PAGE_SIZE"))


def _count_open_fds() -> float:
    return float(len(os.listdir("/proc/self/fd")))


def _install_process_metrics(registry: MetricRegistry) -> None:
    """Deploy-correlation gauges on the default registry:
    ``pio_build_info{version=...} 1`` identifies which build answered a
    scrape (regressions line up with deploys), and
    ``pio_process_start_time_seconds`` lets dashboards compute uptime
    (``time() - pio_process_start_time_seconds``)."""
    from predictionio_tpu.version import __version__

    registry.gauge(
        "pio_build_info",
        "Constant 1, labeled with the running package version",
        ("version",),
    ).labels(__version__).set(1)
    registry.gauge(
        "pio_process_start_time_seconds",
        "Unix time this process's telemetry started",
    ).set(_PROCESS_START_TIME)
    # self-telemetry: resident set + open fds, read at scrape time from
    # /proc so replica memory/fd creep is visible before the OOM killer
    # (or EMFILE) sees it. Registered only where /proc exists — off
    # Linux the families are simply absent, not NaN noise.
    if os.path.isdir("/proc/self"):
        registry.gauge(
            "pio_process_resident_bytes",
            "Resident set size of this process (/proc/self/statm)",
        ).set_function(_read_resident_bytes)
        registry.gauge(
            "pio_process_open_fds",
            "Open file descriptors of this process (/proc/self/fd)",
        ).set_function(_count_open_fds)


_default_registry = MetricRegistry()
_install_process_metrics(_default_registry)


def get_registry() -> MetricRegistry:
    """The process-wide registry every server and training loop feeds."""
    return _default_registry
