"""Text-classification template (gallery parity: labeled documents →
hashed bag-of-words → multinomial NB)."""

from __future__ import annotations

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.textclassification import (
    TextDataSourceParams,
    TextNBAlgorithm,
    TextNBParams,
    TextPreparator,
    TextPreparatorParams,
    TextTrainingData,
    hash_counts,
    textclassification_engine,
    tokenize,
)
from predictionio_tpu.parallel.mesh import ComputeContext

SPAM = [
    "win a free prize now claim your money",
    "free money click now to win big prize",
    "claim your free prize win money now",
    "exclusive offer win money free claim",
]
HAM = [
    "meeting moved to tuesday please review the agenda",
    "please review the quarterly report before the meeting",
    "agenda attached for the tuesday planning meeting",
    "notes from the review meeting attached",
]


@pytest.fixture(scope="module")
def ctx():
    return ComputeContext.create(batch="text-test")


def _seed(storage, app_name="TextApp"):
    app_id = storage.get_meta_data_apps().insert(App(id=0, name=app_name))
    events = storage.get_events()
    events.init(app_id)
    batch = []
    for i, text in enumerate(SPAM):
        batch.append(Event(
            event="$set", entity_type="document", entity_id=f"s{i}",
            properties=DataMap({"text": text, "label": "spam"}),
        ))
    for i, text in enumerate(HAM):
        batch.append(Event(
            event="$set", entity_type="document", entity_id=f"h{i}",
            properties=DataMap({"text": text, "label": "ham"}),
        ))
    events.insert_batch(batch, app_id)
    return app_id


def _train(ctx, storage, n_features=512):
    from predictionio_tpu.models.textclassification import TextDataSource

    ds = TextDataSource(TextDataSourceParams(app_name="TextApp"))
    td = ds.read_training(ctx)
    td.sanity_check()
    prepared = TextPreparator(
        TextPreparatorParams(n_features=n_features)
    ).prepare(ctx, td)
    return TextNBAlgorithm(TextNBParams()).train(ctx, prepared)


class TestHashing:
    def test_tokenize(self):
        assert tokenize("Hello, World! it's 42") == [
            "hello", "world", "it's", "42"
        ]

    def test_hashing_is_process_stable(self):
        # FNV-1a, not builtin hash(): same buckets in every process
        v = hash_counts(["alpha", "beta", "alpha"], 64)
        assert v.sum() == 3.0
        assert (v == hash_counts(["alpha", "beta", "alpha"], 64)).all()
        assert v.max() >= 2.0  # the repeated token stacks

    def test_fixed_width_regardless_of_vocabulary(self):
        a = hash_counts(tokenize("one two three"), 128)
        b = hash_counts(tokenize("totally different words here now"), 128)
        assert a.shape == b.shape == (128,)


class TestTraining:
    def test_classifies_planted_corpus(self, ctx, memory_storage):
        _seed(memory_storage)
        model = _train(ctx, memory_storage)
        algo = TextNBAlgorithm(TextNBParams())
        spam = algo.predict(
            model, {"text": "claim your free money prize"}
        )
        ham = algo.predict(
            model, {"text": "please review the meeting agenda"}
        )
        assert spam["label"] == "spam"
        assert ham["label"] == "ham"
        assert set(spam["scores"]) == {"spam", "ham"}
        assert spam["scores"]["spam"] > spam["scores"]["ham"]

    def test_sanity_checks(self):
        with pytest.raises(ValueError, match="no labeled documents"):
            TextTrainingData(texts=[], labels=[]).sanity_check()
        with pytest.raises(ValueError, match="two distinct labels"):
            TextTrainingData(
                texts=["a", "b"], labels=["x", "x"]
            ).sanity_check()

    def test_batch_matches_single(self, ctx, memory_storage):
        _seed(memory_storage)
        model = _train(ctx, memory_storage)
        algo = TextNBAlgorithm(TextNBParams())
        queries = [{"text": t} for t in ("free prize", "agenda review")]
        batch = algo.batch_predict(model, queries)
        singles = [algo.predict(model, q) for q in queries]
        # float32 matmul sums differ in the last ulp across batch
        # shapes (XLA reassociates); labels and scores agree to 1e-5
        for b, s in zip(batch, singles):
            assert b["label"] == s["label"]
            for lbl in b["scores"]:
                assert b["scores"][lbl] == pytest.approx(
                    s["scores"][lbl], rel=1e-5
                )

    def test_kfold_evaluation_accuracy(self, ctx, memory_storage):
        """read_eval folds feed MetricEvaluator; the planted corpus is
        separable, so held-out accuracy must be high."""
        from predictionio_tpu.core import EngineParams
        from predictionio_tpu.core.evaluation import (
            AverageMetric,
            MetricEvaluator,
        )
        from predictionio_tpu.models.textclassification import (
            textclassification_engine,
        )

        class Accuracy(AverageMetric):
            def calculate_point(self, ei, q, p, a):
                return 1.0 if p["label"] == a else 0.0

        _seed(memory_storage)
        params = EngineParams(
            data_source=(
                "", TextDataSourceParams(app_name="TextApp", eval_k=2)
            ),
            preparator=("", TextPreparatorParams(n_features=512)),
            algorithms=[("nb", TextNBParams())],
        )
        result = MetricEvaluator(Accuracy()).evaluate(
            ctx, textclassification_engine(), [params]
        )
        assert result.best_score.score >= 0.75

    def test_engine_end_to_end(self, ctx, memory_storage):
        from predictionio_tpu.core import EngineParams
        from predictionio_tpu.core.workflow import (
            load_deployment,
            run_train,
        )

        _seed(memory_storage)
        engine = textclassification_engine()
        params = EngineParams(
            data_source=("", TextDataSourceParams(app_name="TextApp")),
            preparator=("", TextPreparatorParams(n_features=512)),
            algorithms=[("nb", TextNBParams())],
        )
        run_train(
            engine, params, engine_id="text", ctx=ctx,
            storage=memory_storage,
        )
        _inst, algorithms, models, serving = load_deployment(
            engine, params, engine_id="text", ctx=ctx,
            storage=memory_storage,
        )
        query = {"text": "win free money now"}
        preds = algorithms[0].batch_predict(models[0], [query])
        assert serving.serve(query, [preds[0]])["label"] == "spam"
