"""Core: DASE controller API + engine assembly + workflow runtime.

TPU-native counterpart of the reference ``core`` module: the controller
SPI (``core/src/main/scala/.../core/Base*.scala``), the developer-facing
controller API (``.../controller``), and the workflow runtime
(``.../workflow``). One deliberate collapse: the reference's P/P2L/L
algorithm trichotomy exists because RDD-backed vs local models behave
differently on Spark (SURVEY.md §2.2); with JAX every model is a pytree
that is either host-resident or mesh-sharded, so there is a single
:class:`~predictionio_tpu.core.controller.Algorithm` base whose
persistence mode covers the distinction.
"""

from predictionio_tpu.core.controller import (
    Algorithm,
    AverageServing,
    DataSource,
    EmptyParams,
    FirstServing,
    IdentityPreparator,
    Params,
    PersistenceMode,
    Preparator,
    Serving,
)
from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.core.registry import engine_registry, register_engine

__all__ = [
    "Algorithm",
    "AverageServing",
    "DataSource",
    "EmptyParams",
    "Engine",
    "EngineParams",
    "FirstServing",
    "IdentityPreparator",
    "Params",
    "PersistenceMode",
    "Preparator",
    "Serving",
    "engine_registry",
    "register_engine",
]
