"""Shared-state race rules (docs/static_analysis.md "Concurrency
rules"): Eraser-style lockset analysis over the thread roots discovered
by :mod:`predictionio_tpu.analysis.threads`.

Three rules, one model:

* ``shared-state-race`` — a ``self._x`` field written on one thread
  root and accessed dangerously on another with no lock common to all
  conflicting sites;
* ``lock-consistency`` — a field guarded by one lock at most dangerous
  sites but bare (or under a different lock) at others: names the
  majority lock and flags every deviating site;
* ``check-then-act`` — a read of ``self._x`` feeding a decision whose
  branch writes the same field, with the lock released between the two
  (two separate ``with`` blocks on the same lock count as released) —
  the reservation-vs-registration / verdict-CAS bug shape.

Exemptions — the idioms this codebase legitimately uses:

* **pre-start init**: accesses in ``__init__`` (and helpers reachable
  only from it) happen before any root thread exists;
* **GIL-atomic publication**: a field whose every write is a plain
  store of a fresh object and whose every read is a single load is
  safe under the GIL — but in-place mutation of the published object
  (``self._pub.append(...)``) or iteration during mutation is NOT, and
  re-enters the analysis;
* **single-writer read-modify-write**: ``self._n += 1`` confined to one
  (single-instance) root with all other roots doing single loads;
* **sync-typed fields**: ``Queue``/``Event``/``Condition``/
  ``Semaphore``/``ContextVar``/``threading.local`` fields mediate the
  handoff themselves.

Dangerous access = write / read-modify-write / in-place mutation /
iteration (dict & set iteration raises ``RuntimeError`` mid-mutation;
list iteration yields torn views). Plain single loads are GIL-atomic
and never conflict on their own.
"""

from __future__ import annotations

import ast
from collections import Counter

from predictionio_tpu.analysis import astutil, threads
from predictionio_tpu.analysis.model import Finding
from predictionio_tpu.analysis.source import SourceModule

DANGEROUS = ("write", "rmw", "mutate", "iter")
WRITES = ("write", "rmw", "mutate")

#: each module's findings depend only on that module's text --
#: cacheable per file (see analysis/cache.py)
PER_FILE = True


def check(modules: list[SourceModule]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        model = threads.get_model(mod)
        if not model.roots:
            continue  # single-threaded module: no race analysis
        findings.extend(_check_fields(mod, model))
        findings.extend(_check_check_then_act(mod, model))
    return findings


# --------------------------------------------------------------------------
# shared-state-race + lock-consistency
# --------------------------------------------------------------------------


class _Site:
    """One access with its root attributions and effective locksets."""

    __slots__ = ("acc", "roots", "locks")

    def __init__(self, acc, roots, locks):
        self.acc = acc
        self.roots = roots  # list[int]
        self.locks = locks  # frozenset of lock ids


def _attributed_sites(model: threads.ThreadModel):
    """{(owner, field): [_Site]} for accesses that run on ≥1 root
    (init-only accesses have no roots and drop out here)."""
    out: dict[tuple[str, str], list[_Site]] = {}
    for qual, info in model.funcs.items():
        roots = model.roots_of(qual)
        if not roots:
            continue
        # entry lockset = intersection over every root that can reach
        # this function: only a lock held on ALL paths protects the
        # access
        entry: frozenset | None = None
        for r in roots:
            e = model.entry_locks(r, qual)
            entry = e if entry is None else entry & e
        for acc in info.accesses:
            locks = threads.tokens_to_locks(acc.held) | (
                entry or frozenset()
            )
            out.setdefault((acc.owner, acc.field), []).append(
                _Site(acc, roots, locks)
            )
    return out


def _effective_root_count(model, root_ids) -> int:
    return sum(2 if model.roots[r].multi else 1 for r in root_ids)


def _check_fields(
    mod: SourceModule, model: threads.ThreadModel
) -> list[Finding]:
    findings: list[Finding] = []
    for (owner, field), sites in sorted(_attributed_sites(model).items()):
        if (owner, field) in model.sync_fields:
            continue
        write_sites = [s for s in sites if s.acc.kind in WRITES]
        if not write_sites:
            continue
        all_roots = set()
        for s in sites:
            all_roots.update(s.roots)
        if _effective_root_count(model, all_roots) < 2:
            continue
        dangerous = [s for s in sites if s.acc.kind in DANGEROUS]
        common = None
        for s in dangerous:
            common = s.locks if common is None else (common & s.locks)
        if common:
            continue  # one lock consistently guards every dangerous site
        # GIL-atomic publication: plain stores + single loads only
        kinds = {s.acc.kind for s in sites}
        if kinds <= {"write", "read"}:
            continue
        # single-writer RMW with atomic readers: every dangerous access
        # confined to one single-instance root
        dangerous_roots = set()
        for s in dangerous:
            dangerous_roots.update(s.roots)
        if _effective_root_count(model, dangerous_roots) < 2:
            continue
        # classes driven only by external callers (route tables built
        # at setup, per-request objects, helpers their owner locks
        # around) are the caller's concurrency story — the rule fires
        # only when a DISCOVERED root (thread/handler/hook/callback)
        # touches the field dangerously
        if all(
            model.roots[r].kind == "external" for r in dangerous_roots
        ):
            continue
        majority = _majority_lock(dangerous)
        if majority is not None:
            lock, holders = majority
            for s in dangerous:
                if lock in s.locks:
                    continue
                state = (
                    f"under {_fmt_locks(s.locks)}"
                    if s.locks
                    else "with no lock"
                )
                findings.append(
                    Finding(
                        rule="lock-consistency",
                        path=mod.rel_path,
                        line=s.acc.line,
                        col=s.acc.col,
                        message=(
                            f"{_fq(owner, field)} is guarded by "
                            f"{lock} at {holders} site(s) but "
                            f"{_what(s.acc.kind)} {state} here "
                            f"(roots: {_root_names(model, s.roots)})"
                        ),
                        context=s.acc.qual,
                        source=mod.source_line(s.acc.line),
                    )
                )
            continue
        # no dominant lock at all: a plain race between named roots
        site = next(
            (s for s in dangerous if s.acc.kind in WRITES and not s.locks),
            dangerous[0],
        )
        other_roots = sorted(all_roots - set(site.roots)) or sorted(
            all_roots
        )
        findings.append(
            Finding(
                rule="shared-state-race",
                path=mod.rel_path,
                line=site.acc.line,
                col=site.acc.col,
                message=(
                    f"{_fq(owner, field)} is {_what(site.acc.kind)} on "
                    f"{_root_names(model, site.roots)} and accessed on "
                    f"{_root_names(model, other_roots)} with no common "
                    "lock"
                ),
                context=site.acc.qual,
                source=mod.source_line(site.acc.line),
            )
        )
    return findings


def _majority_lock(dangerous: list[_Site]) -> tuple[str, int] | None:
    """(lock, site count) when one lock guards ≥2 dangerous sites and
    at least half of them — the field has a de-facto guard and the
    stragglers are deviations, not a designed lock-free field."""
    counts: Counter = Counter()
    for s in dangerous:
        for lock in s.locks:
            counts[lock] += 1
    if not counts:
        return None
    lock, n = counts.most_common(1)[0]
    if n >= 2 and 2 * n >= len(dangerous):
        return lock, n
    return None


def _fq(owner: str, field: str) -> str:
    return f"{owner}.{field}" if owner else field


def _what(kind: str) -> str:
    return {
        "write": "written",
        "rmw": "read-modify-written",
        "mutate": "mutated in place",
        "iter": "iterated",
        "read": "read",
    }[kind]


def _fmt_locks(locks: frozenset) -> str:
    return "/".join(sorted(locks))


def _root_names(model: threads.ThreadModel, root_ids) -> str:
    names = sorted({model.roots[r].display for r in root_ids})
    return ", ".join(names) if names else "<no root>"


# --------------------------------------------------------------------------
# check-then-act
# --------------------------------------------------------------------------


def _check_check_then_act(
    mod: SourceModule, model: threads.ThreadModel
) -> list[Finding]:
    findings: list[Finding] = []
    # fields with ≥2 effective writer roots: only those can have a
    # second thread interpose between the check and the act
    writer_roots: dict[tuple[str, str], set[int]] = {}
    for qual, info in model.funcs.items():
        roots = model.roots_of(qual)
        if not roots:
            continue
        for acc in info.accesses:
            if acc.kind in WRITES:
                writer_roots.setdefault(
                    (acc.owner, acc.field), set()
                ).update(roots)
    contended = {
        key
        for key, roots in writer_roots.items()
        if _effective_root_count(model, roots) >= 2
        and any(model.roots[r].kind != "external" for r in roots)
        and key not in model.sync_fields
    }
    if not contended:
        return findings

    # one statement-lockset + field-test walk per function, shared by
    # the guarded-writes pass and the per-function scan below (each
    # used to rebuild the identical maps for every function)
    walks: dict[str, tuple[list, frozenset]] = {}
    for qual, fn in model.index.funcs.items():
        if model.funcs.get(qual) is None:
            continue
        held_at = _statement_locksets(model, qual, fn)
        walks[qual] = (
            list(_field_tests(model, qual, fn, held_at)),
            _entry_tokens(model, qual),
        )
    guarded = _self_guarded_writes(model, walks)
    for qual in model.index.funcs:
        if not model.roots_of(qual) or qual not in walks:
            continue
        owner = threads.owner_of(model.index, qual)
        findings.extend(
            _scan_cta(
                mod, model, qual, owner, contended, guarded,
                *walks[qual],
            )
        )
    return findings


def _self_guarded_writes(model, walks) -> set[tuple[str, int]]:
    """(qual, line) of writes that re-check their own field under a
    lock held continuously across the check and the write — the CAS /
    double-checked idiom. These are the FIX for check-then-act and must
    not be reported as acts of an outer, weaker check."""
    out: set[tuple[str, int]] = set()
    for qual, (tests, entry) in walks.items():
        info = model.funcs[qual]
        for test, test_held in tests:
            fields, extent = test
            for acc in info.accesses:
                if (
                    acc.kind in WRITES
                    and (acc.owner, acc.field) in fields
                    and extent[0] < acc.line <= extent[1]
                    and (acc.held | entry) & (test_held | entry)
                ):
                    out.add((qual, acc.line))
    return out


def _entry_tokens(model, qual) -> frozenset:
    """Locks provably held on EVERY entry to ``qual`` (over all its
    roots) as continuous pseudo-tokens — a function always called with
    the lock held runs its whole body inside one critical section."""
    roots = model.roots_of(qual)
    if not roots:
        return frozenset()
    locks = None
    for r in roots:
        entry = model.entry_locks(r, qual)
        locks = entry if locks is None else (locks & entry)
    return frozenset(f"{lid}@@entry" for lid in (locks or ()))


def _statement_locksets(model, qual, fn) -> dict[int, frozenset]:
    """{id(stmt): lock tokens held at that statement} — a re-walk of
    the same lexical ``with`` tracking the model's access scan used."""
    held_at: dict[int, frozenset] = {}

    def walk(body, held):
        for stmt in body:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            held_at[id(stmt)] = held
            inner = held
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    lid = model._resolve_lock(item.context_expr, qual)
                    if lid:
                        inner = inner | {model._with_token(lid, stmt)}
            for field in ("body", "orelse", "finalbody"):
                nested = getattr(stmt, field, None)
                if nested:
                    walk(nested, inner)
            for handler in getattr(stmt, "handlers", ()):
                walk(handler.body, inner)
            for case in getattr(stmt, "cases", ()):  # ast.Match
                walk(case.body, inner)

    walk(fn.body, frozenset())
    return held_at


def _field_tests(model, qual, fn, held_at):
    """Yield ((tested fields, (lineno, end_lineno)), held tokens at the
    read) for every If/While whose test reads a self-field — directly,
    or through a local alias assigned from one earlier in the
    function."""
    owner = threads.owner_of(model.index, qual)
    #: name -> (field key, held tokens at the aliasing read)
    aliases: dict[str, tuple[tuple[str, str], frozenset]] = {}
    for stmt in astutil.walk_statements(fn.body):
        held = held_at.get(id(stmt), frozenset())
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Attribute)
            and isinstance(stmt.value.value, ast.Name)
            and stmt.value.value.id in ("self", "cls")
        ):
            aliases[stmt.targets[0].id] = (
                (owner, stmt.value.attr), held,
            )
            continue
        if isinstance(stmt, ast.Assign) and all(
            isinstance(t, ast.Name) for t in stmt.targets
        ):
            for t in stmt.targets:
                aliases.pop(t.id, None)
        if not isinstance(stmt, (ast.If, ast.While)):
            continue
        end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        direct: set[tuple[str, str]] = set()
        via_alias: list[tuple[tuple[str, str], frozenset]] = []
        for node in ast.walk(stmt.test):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")
                and isinstance(node.ctx, ast.Load)
                and not isinstance(
                    astutil.parent_of(node), ast.Call
                )  # self._x() is a call, not a state read
            ):
                direct.add((owner, node.attr))
            elif isinstance(node, ast.Name) and node.id in aliases:
                via_alias.append(aliases[node.id])
        if direct:
            yield (direct, (stmt.lineno, end)), held
        for key, alias_held in via_alias:
            yield ({key}, (stmt.lineno, end)), alias_held


def _scan_cta(mod, model, qual, owner, contended, guarded, tests, entry):
    findings = []
    info = model.funcs[qual]
    seen: set[tuple] = set()
    for (fields, extent), raw_test_held in tests:
        test_held = raw_test_held | entry
        keys = {k for k in fields if k in contended}
        if not keys:
            continue
        # direct writes inside the decision's branches
        for acc in info.accesses:
            if (
                acc.kind in WRITES
                and (acc.owner, acc.field) in keys
                and extent[0] < acc.line <= extent[1]
                and not ((acc.held | entry) & test_held)
                and (qual, acc.line) not in guarded
            ):
                findings.append(
                    _cta_finding(
                        mod, qual, acc.owner, acc.field,
                        extent[0], acc.line, acc.col, test_held,
                        mod.source_line(acc.line),
                    )
                )
                seen.add((acc.owner, acc.field, extent[0]))
        # writes through a same-module helper called in the branches
        for callee, call_held, line in info.calls:
            if not (extent[0] < line <= extent[1]):
                continue
            callee_info = model.funcs.get(callee)
            if callee_info is None:
                continue
            for key in keys:
                w = next(
                    (
                        a
                        for a in callee_info.accesses
                        if a.kind in WRITES
                        and (a.owner, a.field) == key
                    ),
                    None,
                )
                if w is None or (key[0], key[1], extent[0]) in seen:
                    continue
                if (callee, w.line) in guarded:
                    continue
                act_held = call_held | w.held | entry
                if act_held & test_held:
                    continue
                findings.append(
                    _cta_finding(
                        mod, qual, key[0], key[1], extent[0], line, 0,
                        test_held, mod.source_line(line),
                        via=callee,
                    )
                )
                seen.add((key[0], key[1], extent[0]))
    return findings


def _cta_finding(
    mod, qual, owner, field, test_line, act_line, col, test_held,
    source, via: str | None = None,
):
    read_state = (
        f"read under {_fmt_locks(threads.tokens_to_locks(test_held))} "
        "(released before the update)"
        if test_held
        else "read with no lock"
    )
    through = f" through {via}()" if via else ""
    return Finding(
        rule="check-then-act",
        path=mod.rel_path,
        line=act_line,
        col=col,
        message=(
            f"{_fq(owner, field)} checked at line {test_line} "
            f"({read_state}) then written{through} — another thread "
            "can interpose between the check and the act"
        ),
        context=qual,
        source=source,
    )
