"""Query items similar to the given items, with optional filters."""

import argparse
import json

from predictionio_tpu.client import EngineClient


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--url", default="http://127.0.0.1:8000")
    parser.add_argument("--items", default="i0", help="comma-separated")
    parser.add_argument("--num", type=int, default=4)
    parser.add_argument("--categories", default=None)
    args = parser.parse_args()
    query = {"items": args.items.split(","), "num": args.num}
    if args.categories:
        query["categories"] = args.categories.split(",")
    print(json.dumps(EngineClient(args.url).send_query(query), indent=2))


if __name__ == "__main__":
    main()
