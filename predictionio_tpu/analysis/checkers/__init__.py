"""Checker registry for ``pio-tpu lint``.

Each checker is ``check(modules: list[SourceModule]) -> list[Finding]``
over the whole file set at once, so project-wide rules (lock-order
cycles, metric-label consistency) see everything.

A checker whose module sets ``PER_FILE = True`` promises that each
module's findings are a pure function of that module's text alone —
the findings cache (``analysis/cache.py``) replays those from disk for
unchanged files and only re-runs them on misses. Cross-file checkers
(lock graphs, imported-jit call sites, the mesh-axis and metric-name
registries) must NOT set it.
"""

from __future__ import annotations

from predictionio_tpu.analysis.checkers import (
    clock,
    device_sync,
    donation,
    jit_retrace,
    lifecycle,
    locks,
    races,
    sharding_spec,
    telemetry,
    threads,
    wire_contract,
)

_CHECKER_MODULES = (
    locks,
    clock,
    device_sync,
    jit_retrace,
    sharding_spec,
    donation,
    threads,
    races,
    telemetry,
    lifecycle,
    wire_contract,
)

ALL_CHECKERS = tuple(mod.check for mod in _CHECKER_MODULES)

#: module names whose findings are cacheable per file (see docstring);
#: derived from each checker's own PER_FILE attribute so there is one
#: source of truth — a new per-file checker only sets the flag
PER_FILE_CHECKERS = frozenset(
    mod.__name__.rsplit(".", 1)[-1]
    for mod in _CHECKER_MODULES
    if getattr(mod, "PER_FILE", False)
)
