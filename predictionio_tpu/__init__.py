"""predictionio_tpu — a TPU-native machine-learning server framework.

A ground-up rebuild of the *capabilities* of Apache PredictionIO
(incubating) — event collection, DASE engines (DataSource / Preparator /
Algorithm / Serving / Evaluation), train/eval/deploy workflows, pluggable
storage, and REST serving — with the Spark/MLlib compute substrate replaced
by JAX/XLA: training data staged into device arrays sharded over a
``jax.sharding.Mesh``, algorithms compiled with ``jax.jit`` under explicit
sharding, and a predict server dispatching onto pre-compiled TPU executables.

Layer map (mirrors reference SURVEY.md §1, reimagined TPU-first):

* ``predictionio_tpu.data``     — event model + pluggable storage (L2)
* ``predictionio_tpu.core``     — DASE controller API + workflow runtime (L4/L5)
* ``predictionio_tpu.parallel`` — mesh / sharding / collectives (replaces Spark, L3)
* ``predictionio_tpu.ops``      — JAX/Pallas numeric kernels (replaces MLlib)
* ``predictionio_tpu.models``   — engine templates (ALS recommendation,
  Naive Bayes classification, similar-product, e-commerce) (L7)
* ``predictionio_tpu.serving``  — event server + engine server (L1)
* ``predictionio_tpu.cli``      — ``pio``-style console (L6)
"""

from predictionio_tpu.version import __version__

__all__ = ["__version__"]
