"""SQLite storage backend — the durable zero-dependency default.

Plays the role of the reference's JDBC backend for dev/single-host use
(``data/.../storage/jdbc/*.scala``: scalikejdbc against
PostgreSQL/MySQL) using Python's stdlib ``sqlite3``. All DAO logic
lives in :mod:`predictionio_tpu.data.storage.sql_common`, shared with
the networked :mod:`~predictionio_tpu.data.storage.postgres` backend —
this module only supplies the sqlite dialect and connection handling.

Thread-safety: one connection per thread via ``threading.local`` (sqlite
connections are not shareable across threads); WAL mode so the event
server's concurrent reader/writer threads do not serialize on the file.
"""

from __future__ import annotations

import os
import sqlite3
from typing import Any, Sequence

from predictionio_tpu.data.storage.sql_common import (
    SQLAccessKeys,
    SQLApps,
    SQLChannels,
    SQLClient,
    SQLDialect,
    SQLEngineInstances,
    SQLEngineManifests,
    SQLEvaluationInstances,
    SQLEvents,
    SQLModels,
)


class SQLiteDialect(SQLDialect):
    placeholder = "?"
    autoinc_pk = "INTEGER PRIMARY KEY AUTOINCREMENT"
    blob_type = "BLOB"
    integrity_errors = (sqlite3.IntegrityError,)
    operational_errors = (sqlite3.OperationalError,)

    def upsert(self, table: str, cols: Sequence[str],
               pk: Sequence[str]) -> str:
        return (
            f"INSERT OR REPLACE INTO {table} ({','.join(cols)}) "
            f"VALUES ({','.join('?' * len(cols))})"
        )

    def insert_autoinc(self, cur, table: str, cols: Sequence[str],
                       values: Sequence[Any]) -> int:
        cur.execute(
            f"INSERT INTO {table} ({','.join(cols)}) "
            f"VALUES ({','.join('?' * len(cols))})",
            tuple(values),
        )
        return cur.lastrowid


class SQLiteClient(SQLClient):
    """Shared connection manager for all DAOs of one storage source."""

    def __init__(self, config: dict | None = None):
        super().__init__()
        self.dialect = SQLiteDialect()
        config = config or {}
        path = config.get("PATH") or config.get(
            "URL", os.path.join(os.getcwd(), "pio.sqlite")
        )
        if path != ":memory:":
            os.makedirs(
                os.path.dirname(os.path.abspath(path)), exist_ok=True
            )
        self.path = path
        self.ensure_metadata_schema()

    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        return conn


# DAO names kept for the registry and external callers; the bodies are
# the shared SQL implementations.
SQLiteApps = SQLApps
SQLiteAccessKeys = SQLAccessKeys
SQLiteChannels = SQLChannels
SQLiteEngineInstances = SQLEngineInstances
SQLiteEngineManifests = SQLEngineManifests
SQLiteEvaluationInstances = SQLEvaluationInstances
SQLiteModels = SQLModels
SQLiteEvents = SQLEvents
