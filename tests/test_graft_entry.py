"""Driver-contract entry points (`__graft_entry__.py`): the jittable
single-chip forward step and the multi-chip dryrun, swept over mesh
topologies (data × model) so the sharded train + serving steps are
exercised on every axis split an 8-device pod slice can express."""

import sys
import os

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


class TestEntry:
    def test_entry_compiles_and_runs(self):
        import jax

        fn, args = graft.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)


class TestDryrunMeshSweep:
    @pytest.mark.parametrize("shape", [(8, 1), (2, 4), (1, 8)])
    def test_mesh_shape(self, shape):
        """Full sharded training + serving step on each topology:
        pure-data (8x1), mixed (2x4), pure-model (1x8)."""
        graft.dryrun_multichip(8, mesh_shape=shape)

    def test_default_shape_still_2d(self):
        graft.dryrun_multichip(8)

    def test_bad_shape_rejected(self):
        with pytest.raises(AssertionError, match="does not cover"):
            graft.dryrun_multichip(8, mesh_shape=(3, 2))
