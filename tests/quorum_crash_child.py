"""Kill-9 crash-test writer for the replicated (quorum) event path.

The quorum-ack analogue of ``eventlog_crash_child.py``: connects a
``ReplicatedStoreClient`` to the store-server peer URLs in argv and
inserts events one at a time, printing ``ACK <i> <event_id>`` —
flushed — only AFTER the W-of-N quorum write returned. The parent test
SIGKILLs this process mid-stream and asserts every acked event is
durable on EVERY peer (W equals N here): the zero-ack'd-write-loss
contract of docs/storage.md "Replication & failover".

Usage: python tests/quorum_crash_child.py <hint-dir> <url> [<url> ...]
"""

from __future__ import annotations

import datetime as dt
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from predictionio_tpu.data import DataMap, Event  # noqa: E402
from predictionio_tpu.data.storage.replicated import (  # noqa: E402
    ReplicatedStoreClient,
)

APP_ID = 1


def main() -> int:
    hint_dir, urls = sys.argv[1], sys.argv[2:]
    client = ReplicatedStoreClient(
        {
            "URLS": ",".join(urls),
            "W": str(len(urls)),  # every ack means durable EVERYWHERE
            "HINT_DIR": hint_dir,
        }
    )
    events = client.dao("events")
    events.init(APP_ID)
    t0 = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
    i = 0
    while True:
        event = Event(
            event="rate",
            entity_type="user",
            entity_id=f"u{i}",
            properties=DataMap({"n": i}),
            event_time=t0 + dt.timedelta(seconds=i),
        )
        event_id = events.insert(event, APP_ID)
        # the ack the parent trusts: printed strictly after W peers
        # reported the write durable
        print(f"ACK {i} {event_id}", flush=True)
        i += 1


if __name__ == "__main__":
    sys.exit(main())
