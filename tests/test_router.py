"""Scale-out serving router (serving/router.py).

Failover semantics against REAL HTTP replicas (fake handlers on the
framework's own HTTP layer, so drain/healthz behavior is the genuine
article): replica death mid-request, all-replicas-draining, breaker
exclusion + half-open readmission, warmup-gated admission, and the
rolling generation swap's zero-drop guarantee."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.serving import resilience
from predictionio_tpu.serving.http import (
    HTTPError,
    HTTPServer,
    Response,
    Router,
)
from predictionio_tpu.serving.router import (
    DRAINING,
    HEALTHY,
    RETIRED,
    UNHEALTHY,
    WARMING,
    Replica,
    ServingRouter,
)


class FakeReplica:
    """A replica-shaped HTTP server with scriptable behavior."""

    def __init__(self, name: str, warm: float = 1.0):
        self.name = name
        self.warm = warm
        self.fail_next = 0  # respond 500 to this many requests
        self.reset_next = 0  # slam the connection on this many
        self.shed_next = 0  # 503 + Retry-After (admission shed) on
        self.shed_hint = "0.30"  # ... this many, with this hint
        self.delay_s = 0.0
        self.calls = 0
        self.seen_deadlines: list[str | None] = []
        self._lock = threading.Lock()
        router = Router()
        router.route("POST", "/queries.json", self._queries)
        router.route("POST", "/batch/queries.json", self._queries)
        router.route("GET", "/metrics.json", self._metrics)
        self.http = HTTPServer(
            router, host="127.0.0.1", port=0, service=f"replica-{name}"
        )
        self.http.start()
        self.url = f"http://127.0.0.1:{self.http.port}"

    def _queries(self, request) -> Response:
        with self._lock:
            self.calls += 1
            self.seen_deadlines.append(
                request.headers.get(resilience.DEADLINE_HEADER)
            )
            if self.reset_next > 0:
                self.reset_next -= 1
                raise resilience.ChaosReset()  # dies mid-request
            if self.fail_next > 0:
                self.fail_next -= 1
                raise HTTPError(500, "injected replica failure")
            if self.shed_next > 0:
                self.shed_next -= 1
                return Response(
                    503,
                    {"message": "server overloaded"},
                    headers={"Retry-After": self.shed_hint},
                )
        if self.delay_s:
            time.sleep(self.delay_s)
        q = json.loads(request.body)
        return Response(
            200, {"result": q.get("x"), "replica": self.name}
        )

    def _metrics(self, request) -> Response:
        return Response(
            200,
            {
                "pio_warmup_complete": {
                    "type": "gauge",
                    "samples": [{"labels": {}, "value": self.warm}],
                }
            },
        )

    def close(self) -> None:
        self.http.shutdown()


def make_router(*replicas: FakeReplica, **kwargs) -> ServingRouter:
    kwargs.setdefault("probe_interval_s", 0.05)
    kwargs.setdefault("probe_timeout_s", 2.0)
    kwargs.setdefault("unhealthy_after", 1)
    kwargs.setdefault("registry", MetricRegistry())
    kwargs.setdefault(
        "breaker_config",
        resilience.BreakerConfig(failure_threshold=2, reset_after_s=0.25),
    )
    router = ServingRouter(**kwargs)
    for rep in replicas:
        router.add_replica(rep.url, replica_id=rep.name)
    return router


def wait_for(cond, timeout_s: float = 10.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


@pytest.fixture()
def pair():
    """Two healthy fake replicas behind a bound router."""
    a, b = FakeReplica("a"), FakeReplica("b")
    router = make_router(a, b, failover_retries=1)
    http = router.serve(host="127.0.0.1", port=0)
    http.start()
    assert wait_for(
        lambda: set(router.replica_states().values()) == {HEALTHY}
    ), router.replica_states()
    try:
        yield router, http, a, b
    finally:
        router.close()
        http.shutdown()
        a.close()
        b.close()


def post(base: str, path: str, body, headers=None, timeout=10):
    req = urllib.request.Request(
        f"{base}{path}",
        data=json.dumps(body).encode(),
        headers=headers or {},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), e.headers


def counter_value(registry: MetricRegistry, name: str, **labels):
    data = registry.to_dict()
    for sample in data.get(name, {}).get("samples", ()):
        if all(sample["labels"].get(k) == v for k, v in labels.items()):
            return sample.get("value", sample.get("count"))
    return None


class TestFailover:
    def test_replica_death_mid_request_retries_sibling(self, pair):
        """The connection is severed MID-REQUEST (after the replica
        accepted it); the router retries the sibling inside the
        deadline budget and the client sees a clean 200."""
        router, http, a, b = pair
        a.reset_next = 5
        b.reset_next = 0
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 7},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 200 and body["result"] == 7
        assert body["replica"] == "b"
        assert counter_value(
            router._registry, "pio_router_failovers_total"
        ) == 1

    def test_failover_decrements_deadline_budget(self, pair):
        router, http, a, b = pair
        a.reset_next = 1
        b.reset_next = 1  # both die: retries exhausted -> 502
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 502
        assert "failed" in body["message"]
        # both replicas saw a decremented (never amplified) budget
        seen = [
            float(h) for h in a.seen_deadlines + b.seen_deadlines if h
        ]
        assert seen and all(v <= 10000 for v in seen)

    def test_expired_deadline_rejected_before_routing(self, pair):
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-PIO-Deadline": "0"},
        )
        assert status == 504
        assert a.calls == 0 and b.calls == 0

    def test_4xx_passes_through_without_failover(self, pair):
        """A replica ANSWERING with 4xx is health, not failure — the
        router must not mask it or burn a retry."""
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(base, "/nope.json", {"x": 1})
        assert status == 404  # router's own router: no such route
        a.fail_next = 0
        # upstream 404 via batch route patched to 400: use bad JSON body
        req = urllib.request.Request(
            f"{base}/queries.json", data=b"{not json",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
        assert counter_value(
            router._registry, "pio_router_failovers_total"
        ) in (None, 0)


class TestDraining:
    def test_all_replicas_draining_503_retry_after(self, pair):
        router, http, a, b = pair
        a.http.begin_drain()
        b.http.begin_drain()
        assert wait_for(
            lambda: set(router.replica_states().values()) == {DRAINING}
        ), router.replica_states()
        base = f"http://127.0.0.1:{http.port}"
        status, body, headers = post(base, "/queries.json", {"x": 1})
        assert status == 503
        assert headers.get("Retry-After")
        assert "draining" in body["message"]

    def test_draining_replica_excluded_but_sibling_serves(self, pair):
        router, http, a, b = pair
        a.http.begin_drain()
        assert wait_for(
            lambda: router.replica_states()["a"] == DRAINING
        )
        base = f"http://127.0.0.1:{http.port}"
        for i in range(5):
            status, body, _ = post(base, "/queries.json", {"x": i})
            assert status == 200 and body["replica"] == "b"


class TestBreaker:
    def test_open_breaker_excluded_then_readmitted_half_open(self):
        # own router: a WIDE reset window (vs the pair fixture's
        # 0.25s) so the exclusion phase cannot race into half-open on
        # a slow runner and see a legitimate probe hit the replica
        a, b = FakeReplica("a"), FakeReplica("b")
        router = make_router(
            a, b, failover_retries=1,
            breaker_config=resilience.BreakerConfig(
                failure_threshold=2, reset_after_s=1.5
            ),
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert wait_for(
                lambda: set(router.replica_states().values())
                == {HEALTHY}
            )
            # trip a's breaker (threshold 2); each 500 fails over to b
            a.fail_next = 10
            for i in range(3):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200 and body["replica"] == "b"
            with router._lock:
                breaker_a = router._replicas["a"].breaker
            assert breaker_a.state == resilience.OPEN
            calls_while_open = a.calls
            for i in range(5):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200 and body["replica"] == "b"
            # open breaker: a never even saw a request
            assert a.calls == calls_while_open
            # recovery: past the reset window the next request is a's
            # half-open probe (recovering replicas are probed first)
            a.fail_next = 0
            time.sleep(1.6)
            served_by_a = False
            for i in range(10):
                status, body, _ = post(base, "/queries.json", {"x": i})
                assert status == 200
                if body["replica"] == "a":
                    served_by_a = True
                    break
            assert served_by_a, "recovered replica never probed back in"
            assert breaker_a.state == resilience.CLOSED
        finally:
            router.close()
            http.shutdown()
            a.close()
            b.close()

    def test_failed_half_open_probe_fails_over_and_reopens(self, pair):
        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        a.fail_next = 100
        for i in range(3):
            post(base, "/queries.json", {"x": i})
        with router._lock:
            breaker_a = router._replicas["a"].breaker
        assert breaker_a.state == resilience.OPEN
        time.sleep(0.3)  # reset window elapses; a STILL broken
        status, body, _ = post(base, "/queries.json", {"x": 1})
        assert status == 200 and body["replica"] == "b"
        assert breaker_a.state == resilience.OPEN


class TestAdmission:
    def test_cold_replica_not_admitted_until_warm(self):
        rep = FakeReplica("cold", warm=0.0)
        router = make_router(rep)
        try:
            time.sleep(0.3)
            assert router.replica_states() == {"cold": WARMING}
            rep.warm = 1.0
            assert wait_for(
                lambda: router.replica_states() == {"cold": HEALTHY}
            )
        finally:
            router.close()
            rep.close()

    def test_dead_replica_marked_unhealthy_then_readmitted(self):
        rep = FakeReplica("flappy")
        router = make_router(rep)
        try:
            assert wait_for(
                lambda: router.replica_states() == {"flappy": HEALTHY}
            )
            port = rep.http.port
            rep.http.shutdown()
            assert wait_for(
                lambda: router.replica_states() == {"flappy": UNHEALTHY}
            )
            # a new process binds the same port (kill + respawn in place)
            rep2 = FakeReplica("flappy2")
            # point the router's replica at the new port by rebinding
            # the URL (same effect as a respawn on the original port,
            # without racing the OS for the freed port number)
            with router._lock:
                router._replicas["flappy"].url = rep2.url
            assert wait_for(
                lambda: router.replica_states() == {"flappy": HEALTHY}
            )
            rep2.close()
        finally:
            router.close()
            rep.close()

    def test_no_replicas_503(self):
        router = make_router()
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            status, body, headers = post(
                f"http://127.0.0.1:{http.port}", "/queries.json", {"x": 1}
            )
            assert status == 503 and headers.get("Retry-After")
        finally:
            router.close()
            http.shutdown()


class TestSelection:
    @staticmethod
    def _router():
        # no probe loop: these tests hand-set replica states and the
        # prober would flip unreachable URLs to unhealthy mid-assert
        return make_router(probe_interval_s=999.0)

    def _replicas(self, router, n):
        return [
            router.add_replica(
                f"http://127.0.0.1:{9000 + i}", replica_id=f"r{i}"
            )
            for i in range(n)
        ]

    def test_least_inflight_wins(self):
        router = self._router()
        try:
            reps = self._replicas(router, 3)
            for r in reps:
                r.state = HEALTHY
            reps[0]._inflight = 5
            reps[1]._inflight = 1
            reps[2]._inflight = 5
            picked = router._candidates(b"key", set())[0]
            assert picked.replica_id == "r1"
        finally:
            router.close()

    def test_affinity_breaks_ties_stably(self):
        router = self._router()
        try:
            reps = self._replicas(router, 4)
            for r in reps:
                r.state = HEALTHY
            first = router._candidates(b"user-42", set())[0]
            for _ in range(10):
                assert (
                    router._candidates(b"user-42", set())[0]
                    is first
                )
            # different keys spread across replicas
            picks = {
                router._candidates(f"u{i}".encode(), set())[0].replica_id
                for i in range(50)
            }
            assert len(picks) > 1
        finally:
            router.close()

    def test_ring_stability_across_membership_change(self):
        """Removing one tied replica only remaps keys that hashed to
        it — every other key keeps its replica (consistent hashing,
        not modulo)."""
        router = self._router()
        try:
            reps = self._replicas(router, 4)
            for r in reps:
                r.state = HEALTHY
            keys = [f"key-{i}".encode() for i in range(80)]
            before = {
                k: router._candidates(k, set())[0].replica_id
                for k in keys
            }
            victim = "r2"
            with router._lock:
                router._replicas.pop(victim)
            after = {
                k: router._candidates(k, set())[0].replica_id
                for k in keys
            }
            moved = [
                k for k in keys
                if before[k] != victim and after[k] != before[k]
            ]
            assert not moved, f"{len(moved)} unrelated keys remapped"
        finally:
            router.close()


class TestRollingSwap:
    def test_swap_zero_dropped_inflight(self):
        """An in-flight request on the OLD generation finishes 200
        while the swap drains it; the new generation takes over."""
        old = FakeReplica("old")
        old.delay_s = 0.4
        router = make_router(old, failover_retries=0)
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        new = FakeReplica("new")
        try:
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            results = {}

            def slow_query():
                results["slow"] = post(
                    base, "/queries.json", {"x": 5}, timeout=15
                )

            t = threading.Thread(target=slow_query)
            t.start()
            assert wait_for(lambda: old.calls >= 1, timeout_s=5)
            drained = []
            record = router.rolling_swap(
                new.url,
                generation="g2",
                replica_id="new",
                retire="others",
                wait=True,
            )
            t.join(timeout=15)
            status, body, _ = results["slow"]
            assert status == 200 and body["result"] == 5
            assert record["phase"] == "done"
            assert record["retired"] == ["old"]
            assert router.replica_states() == {"new": HEALTHY}
            # the new generation serves now
            status, body, _ = post(base, "/queries.json", {"x": 9})
            assert status == 200 and body["replica"] == "new"
        finally:
            router.close()
            http.shutdown()
            old.close()
            new.close()

    def test_swap_aborts_when_new_replica_never_warms(self):
        old = FakeReplica("old")
        cold = FakeReplica("cold", warm=0.0)
        router = make_router(old)
        try:
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            record = router.rolling_swap(
                cold.url,
                generation="g2",
                replica_id="cold",
                warm_timeout_s=0.5,
                wait=True,
            )
            assert record["phase"] == "failed"
            assert "never became healthy" in record["error"]
            # the old generation is untouched; the dud is gone
            assert router.replica_states() == {"old": HEALTHY}
        finally:
            router.close()
            old.close()
            cold.close()

    def test_swap_retires_old_via_sigterm_pid(self):
        """A locally-supervised old replica (registered with a pid)
        receives SIGTERM when its drain completes."""
        import os
        import signal as _signal

        received = []
        handler = _signal.signal(
            _signal.SIGTERM, lambda s, f: received.append(s)
        )
        old = FakeReplica("old")
        new = FakeReplica("new")
        router = make_router()
        try:
            router.add_replica(
                old.url, replica_id="old", pid=os.getpid()
            )
            assert wait_for(
                lambda: router.replica_states()["old"] == HEALTHY
            )
            record = router.rolling_swap(
                new.url, generation="g2", replica_id="new", wait=True
            )
            assert record["phase"] == "done"
            assert received == [_signal.SIGTERM]
        finally:
            _signal.signal(_signal.SIGTERM, handler)
            router.close()
            old.close()
            new.close()


class TestAdminRoutes:
    @pytest.fixture()
    def gated(self):
        from predictionio_tpu.serving.config import ServerConfig

        rep = FakeReplica("a")
        router = make_router(
            server_config=ServerConfig(
                key_auth_enforced=True, access_key="sekrit"
            ),
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            yield router, f"http://127.0.0.1:{http.port}", rep
        finally:
            router.close()
            http.shutdown()
            rep.close()

    def test_register_requires_key(self, gated):
        router, base, rep = gated
        status, _, _ = post(base, "/admin/replicas", {"url": rep.url})
        assert status == 401
        status, body, _ = post(
            base, "/admin/replicas",
            {"id": "a", "url": rep.url, "generation": "g1"},
            headers={"X-PIO-Server-Key": "sekrit"},
        )
        assert status == 201 and body["id"] == "a"
        assert wait_for(lambda: router.replica_states() == {"a": HEALTHY})
        # queries stay open (no key needed)
        status, body, _ = post(base, "/queries.json", {"x": 3})
        assert status == 200 and body["result"] == 3

    def test_duplicate_id_conflict(self, gated):
        router, base, rep = gated
        key = {"X-PIO-Server-Key": "sekrit"}
        status, _, _ = post(
            base, "/admin/replicas", {"id": "a", "url": rep.url},
            headers=key,
        )
        assert status == 201
        status, body, _ = post(
            base, "/admin/replicas", {"id": "a", "url": rep.url},
            headers=key,
        )
        assert status == 409

    def test_retire_via_delete(self, gated):
        router, base, rep = gated
        key = {"X-PIO-Server-Key": "sekrit"}
        post(base, "/admin/replicas", {"id": "a", "url": rep.url},
             headers=key)
        assert wait_for(lambda: router.replica_states() == {"a": HEALTHY})
        req = urllib.request.Request(
            f"{base}/admin/replicas/a", method="DELETE",
            headers=key,
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
        assert wait_for(lambda: router.replica_states() == {})
        # listed as retired
        req = urllib.request.Request(
            f"{base}/admin/replicas", headers=key
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            listing = json.loads(resp.read())
        assert [r["id"] for r in listing["retired"]] == ["a"]
        assert listing["retired"][0]["state"] == RETIRED


class TestTracing:
    def test_forward_joins_the_request_trace(self, pair):
        """The replica's root span carries the SAME trace ID the
        client sent, parented under the router's forward span."""
        from predictionio_tpu.obs import tracing

        router, http, a, b = pair
        base = f"http://127.0.0.1:{http.port}"
        tracer = tracing.get_tracer()
        status, _, _ = post(
            base, "/queries.json", {"x": 1},
            headers={"X-Request-ID": "trace-router-1"},
        )
        assert status == 200
        spans = [
            s
            for t in tracer.to_dict().get("traces", [])
            for s in t.get("spans", [])
            if s.get("traceId") == "trace-router-1"
        ]
        names = {s["name"] for s in spans}
        assert any(n.startswith("router ") for n in names), names
        assert any(n.startswith("router/forward") for n in names), names
        # the replica runs in-process here too, so its root span landed
        # in the same process tracer under the same trace id
        assert any(n.startswith("replica-") for n in names), names


class TestSaturationBackpressure:
    """A replica shedding 503 + Retry-After is soft-unhealthy, not
    sick: breaker success, failover to a sibling, deprioritized in
    selection, and a router-level shed once EVERYONE is saturated
    (docs/robustness.md "Overload & backpressure")."""

    def test_shed_fails_over_without_breaker_failure(self, pair):
        router, http, a, b = pair
        a.shed_next = 5
        base = f"http://127.0.0.1:{http.port}"
        status, body, _ = post(
            base, "/queries.json", {"x": 3},
            headers={"X-PIO-Deadline": "10000"},
        )
        assert status == 200 and body["replica"] == "b"
        with router._lock:
            rep_a = router._replicas["a"]
        # the shed marked it saturated for the hinted window, and its
        # breaker saw an ANSWER, not a failure
        assert rep_a.saturated
        assert rep_a.breaker.state == resilience.CLOSED
        # while saturated, traffic prefers the sibling outright
        for _ in range(3):
            status, body, _ = post(base, "/queries.json", {"x": 4})
            assert status == 200 and body["replica"] == "b"

    def test_all_saturated_sheds_at_router_with_soonest_hint(self, pair):
        router, http, a, b = pair
        a.shed_next = 2
        b.shed_next = 2
        base = f"http://127.0.0.1:{http.port}"
        status, body, headers = post(
            base, "/queries.json", {"x": 5},
            headers={"X-PIO-Deadline": "10000"},
        )
        # both replicas answered a shed: the router relays the
        # backpressure (503 + computed hint), never a 502
        assert status == 503
        hint = headers.get("Retry-After")
        assert hint is not None and 0 < float(hint) <= 5.0
        assert "saturated" in body["message"]
        assert counter_value(
            router._registry, "pio_router_shed_total"
        ) == 1
        # next request, with both replicas still inside their hint
        # window: shed at the router BEFORE burning a replica's budget
        calls_before = a.calls + b.calls
        status, _, headers = post(base, "/queries.json", {"x": 6})
        assert status == 503 and headers.get("Retry-After")
        assert a.calls + b.calls == calls_before
        # once the hint window passes, traffic flows again
        assert wait_for(
            lambda: post(base, "/queries.json", {"x": 7})[0] == 200,
            timeout_s=5,
        )

    def test_critical_class_bypasses_router_shed(self, pair):
        from predictionio_tpu.serving import admission

        router, http, a, b = pair
        a.shed_next = 1
        b.shed_next = 1
        base = f"http://127.0.0.1:{http.port}"
        # saturate both marks
        post(base, "/queries.json", {"x": 1},
             headers={"X-PIO-Deadline": "10000"})
        with router._lock:
            assert all(r.saturated for r in router._replicas.values())
        # a critical request is still FORWARDED (the replicas' own
        # admission keeps the full limit open for it) — and they are
        # no longer shedding, so it serves
        calls_before = a.calls + b.calls
        status, _, _ = post(
            base, "/queries.json", {"x": 2},
            headers={admission.CRITICALITY_HEADER: "critical"},
        )
        assert status == 200
        assert a.calls + b.calls > calls_before

    def test_criticality_header_forwarded_to_replica(self, pair):
        from predictionio_tpu.serving import admission

        router, http, a, b = pair
        seen = []
        orig_a, orig_b = a._queries, b._queries

        def spy(rep_orig):
            def _h(request):
                seen.append(
                    request.headers.get(admission.CRITICALITY_HEADER)
                )
                return rep_orig(request)
            return _h

        a._queries = spy(orig_a)
        b._queries = spy(orig_b)
        # rebuild routes to pick up the spies
        for rep in (a, b):
            rep.http.router._routes = []
            rep.http.router.route("POST", "/queries.json", rep._queries)
            rep.http.router.route("GET", "/metrics.json", rep._metrics)
        base = f"http://127.0.0.1:{http.port}"
        status, _, _ = post(
            base, "/queries.json", {"x": 9},
            headers={admission.CRITICALITY_HEADER: "sheddable"},
        )
        assert status == 200
        assert seen == ["sheddable"]

    def test_empty_pool_hint_is_computed_not_hardcoded(self):
        router = make_router()  # no replicas at all
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            base = f"http://127.0.0.1:{http.port}"
            status, _, headers = post(base, "/queries.json", {"x": 1})
            assert status == 503
            hint = headers.get("Retry-After")
            # 2x the probe interval (0.05 in tests) — the recovery
            # cadence, not the legacy constant "1"
            assert hint == "0.10"
        finally:
            router.close()
            http.shutdown()
