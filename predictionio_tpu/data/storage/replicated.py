"""Replicated store tier — quorum writes, failover reads, anti-entropy.

The last single point of failure after the serving tier (PR 6/11), the
trainer (PR 9), and the router (PR 11) became crash-safe was the store
server: every ingested event and every published model generation lived
on exactly ONE node. The reference framework delegated durability to
HBase/PostgreSQL replication (PAPER.md §0 — pluggable event/model
persistence); this module provides the equivalent natively, as a
*client-side* replication layer over N ordinary ``storeserver``
processes (Dynamo-style — peers never talk to each other on the write
path, so a peer is just the unmodified PR 8 store server with its
``PIO_EVENTLOG_FSYNC`` commit path):

* **Quorum writes** — every write fans out to all N peers and acks to
  the caller only after W report durable. Event inserts carry an
  ``X-PIO-Store-Seq`` token (``<writer>:<seq>``) so a replay after a
  torn send is idempotent even on the append-only eventlog backend.
* **Failover reads with read-repair** — reads serve from any live peer
  (sticky preference, advancing on failure); model blob reads verify
  against the generation's SHA-256 manifest and backfill stale or
  corrupt peers from a healthy one.
* **Hinted handoff** — writes a down peer missed are queued on disk
  (bounded, ``atomic_write_bytes``) and drained by a background thread
  when the peer answers again.
* **Anti-entropy** (:class:`AntiEntropyLoop`, runs inside each store
  server given ``--peer`` URLs) — periodically compares per-app event
  watermarks, model-id sets, and metadata between peers and pulls the
  delta, so a restarted node converges without operator action.

Config (``PIO_STORAGE_SOURCES_<NAME>_*`` with ``TYPE=replicated``):

* ``URLS`` — comma-separated peer base URLs (required, ≥ 1)
* ``W`` — write quorum (default: majority, ``N // 2 + 1``)
* ``KEY`` / ``TIMEOUT`` / ``CACERT`` / ``VERIFY`` — per-peer client
  settings, same meaning as the httpstore source
* ``HINT_DIR`` — hint-queue directory (default
  ``$PIO_FS_BASEDIR/replication_hints``)
* ``HINT_LIMIT`` — max queued hints per peer (default 512, drop-oldest)

Env: ``PIO_STORE_HINT_INTERVAL`` (hint-drain poll seconds, default 2),
``PIO_STORE_SYNC_INTERVAL`` (anti-entropy cadence seconds, default 5).
Full semantics, failure matrix, and metric/header tables:
docs/storage.md "Replication & failover".
"""

from __future__ import annotations

import base64
import concurrent.futures
import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Iterable

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeysBackend,
    App,
    AppsBackend,
    Channel,
    ChannelsBackend,
    EngineInstance,
    EngineInstancesBackend,
    EngineManifest,
    EngineManifestsBackend,
    EvaluationInstance,
    EvaluationInstancesBackend,
    EventsBackend,
    Model,
    ModelsBackend,
    PartialBatchError,
    StorageError,
)
from predictionio_tpu.data.storage.httpstore import (
    HTTPAccessKeys,
    HTTPApps,
    HTTPChannels,
    HTTPEngineInstances,
    HTTPEngineManifests,
    HTTPEvaluationInstances,
    HTTPEvents,
    HTTPModels,
    HTTPStoreClient,
    access_key_from_json,
    access_key_to_json,
    app_from_json,
    app_to_json,
    channel_from_json,
    channel_to_json,
    engine_instance_from_json,
    engine_instance_to_json,
    evaluation_instance_from_json,
    evaluation_instance_to_json,
    manifest_from_json,
    manifest_to_json,
)
from predictionio_tpu.data.storage.localfs import atomic_write_bytes
from predictionio_tpu.obs import timeline as timeline_mod
from predictionio_tpu.obs.registry import get_registry

logger = logging.getLogger(__name__)

#: generation manifests live beside their blob under this suffix
#: (core/persistence.manifest_id) — replication orders blob-before-
#: manifest on repair so the manifest stays the commit point
_MANIFEST_SUFFIX = ".manifest"

_DEFAULT_HINT_LIMIT = 512


def _env_interval(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        return default
    return value if value > 0 else default


def hint_interval() -> float:
    """``PIO_STORE_HINT_INTERVAL`` — seconds between hint-drain polls."""
    return _env_interval("PIO_STORE_HINT_INTERVAL", 2.0)


def sync_interval() -> float:
    """``PIO_STORE_SYNC_INTERVAL`` — anti-entropy cadence in seconds."""
    return _env_interval("PIO_STORE_SYNC_INTERVAL", 5.0)


def _register_metrics(registry):
    """The replication telemetry trio (idempotent re-registration)."""
    lag = registry.gauge(
        "pio_store_replica_lag_seconds",
        "seconds the peer's newest event trails the local newest event",
        ("peer",),
    )
    hints = registry.gauge(
        "pio_store_hints_pending",
        "hinted-handoff writes queued on disk for a down peer",
        ("peer",),
    )
    repairs = registry.counter(
        "pio_store_repair_total",
        "replication repair actions by outcome "
        "(events/models/metadata pulls, read_repair backfills, errors)",
        ("outcome",),
    )
    return lag, hints, repairs


def _record(kind: str, message: str, **kw) -> None:
    """Timeline emission through the process-global ring — the store
    server installs its own ring, so failover/repair transitions land
    beside its other lifecycle events."""
    try:
        timeline_mod.get_timeline().record(kind, message, **kw)
    except Exception:  # noqa: BLE001 - telemetry must not fail the op
        logger.exception("timeline record failed")


class ReplicationError(StorageError):
    """A write could not reach its W-of-N quorum."""


# --------------------------------------------------------------------------
# peers
# --------------------------------------------------------------------------


_PEER_CONF_KEYS = ("KEY", "TIMEOUT", "CACERT", "VERIFY")


class Peer:
    """One store-server endpoint: the httpstore client plus its DAOs.

    The underlying :class:`HTTPStoreClient` already carries the PR 3/8
    resilience machinery — per-target circuit breaker, deadline-budget
    propagation, jittered retries on idempotent methods — so this layer
    adds nothing on the single-peer path.
    """

    def __init__(self, url: str, conf: dict | None = None):
        conf = conf or {}
        cfg = {"URL": url}
        for key in _PEER_CONF_KEYS:
            if conf.get(key) not in (None, ""):
                cfg[key] = conf[key]
        self.url = url.rstrip("/")
        self.client = HTTPStoreClient(cfg)
        #: host:port — breaker identity and metric label
        self.name = self.client._target
        self.apps = HTTPApps(self.client)
        self.access_keys = HTTPAccessKeys(self.client)
        self.channels = HTTPChannels(self.client)
        self.engine_instances = HTTPEngineInstances(self.client)
        self.engine_manifests = HTTPEngineManifests(self.client)
        self.evaluation_instances = HTTPEvaluationInstances(self.client)
        self.models = HTTPModels(self.client)
        self.events = HTTPEvents(self.client)

    def healthy(self) -> bool:
        """One cheap liveness probe (GET /) — used before draining
        hints; the breaker already gates the request itself."""
        try:
            out = self.client.json("GET", "/")
            return bool(out)
        except StorageError:
            return False

    def breaker_state(self) -> str:
        return self.client._breaker.state

    def close(self) -> None:
        self.client.close()


# --------------------------------------------------------------------------
# hinted handoff
# --------------------------------------------------------------------------


class HintQueue:
    """Bounded on-disk FIFO of writes one peer missed.

    One JSON file per hint, written with ``atomic_write_bytes`` so a
    crash mid-enqueue never leaves a torn hint; ordered by a
    zero-padded sequence number recovered from the directory on
    restart. At ``limit`` the OLDEST hint is dropped (the peer has been
    down long enough that anti-entropy will do the heavy lifting
    anyway — the queue only needs to cover short outages cheaply).
    """

    def __init__(self, base_dir: str, peer_name: str, limit: int):
        safe = peer_name.replace(":", "_").replace("/", "_")
        self.dir = os.path.join(base_dir, safe)
        os.makedirs(self.dir, exist_ok=True)
        self.limit = max(1, int(limit))
        self.dropped = 0
        self._lock = threading.Lock()
        self._next = 1 + max(
            (
                int(name[:-5])
                for name in os.listdir(self.dir)
                if name.endswith(".json") and name[:-5].isdigit()
            ),
            default=0,
        )

    def _files(self) -> list[str]:
        return sorted(
            name
            for name in os.listdir(self.dir)
            if name.endswith(".json") and name[:-5].isdigit()
        )

    def pending(self) -> int:
        with self._lock:
            return len(self._files())

    def append(self, payload: dict) -> None:
        with self._lock:
            files = self._files()
            while len(files) >= self.limit:
                oldest = files.pop(0)
                try:
                    os.remove(os.path.join(self.dir, oldest))
                except FileNotFoundError:
                    pass
                self.dropped += 1
            path = os.path.join(self.dir, f"{self._next:020d}.json")
            self._next += 1
            atomic_write_bytes(
                path, json.dumps(payload, sort_keys=True).encode("utf-8")
            )

    def drain(self, apply: Callable[[dict], None]) -> int:
        """Replay hints in order; a :class:`StorageError` from ``apply``
        (transport — the peer went away again) stops the drain and
        KEEPS the hint; any other exception marks the hint poison
        (malformed payload, unknown op — replaying it can never
        succeed) and drops it so one bad hint cannot wedge the queue
        or kill the drainer thread. Returns replayed count."""
        replayed = 0
        while True:
            with self._lock:
                files = self._files()
            if not files:
                return replayed
            path = os.path.join(self.dir, files[0])
            try:
                with open(path, "rb") as f:
                    payload = json.loads(f.read().decode("utf-8"))
            except (OSError, ValueError):
                # torn/garbage hint: drop it rather than wedge the queue
                self._drop(path)
                continue
            try:
                apply(payload)
            except StorageError:
                raise  # peer unreachable -> stop, keep the hint
            except Exception:  # noqa: BLE001 - poison hint
                logger.exception("dropping undeliverable hint %s", path)
                self._drop(path)
                continue
            with self._lock:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            replayed += 1

    def _drop(self, path: str) -> None:
        with self._lock:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            self.dropped += 1


# --------------------------------------------------------------------------
# the replicated client
# --------------------------------------------------------------------------


#: metadata kinds the hint/anti-entropy machinery understands:
#: kind -> (peer DAO attr, to_json, from_json)
_META_KINDS = {
    "apps": ("apps", app_to_json, app_from_json),
    "access_keys": ("access_keys", access_key_to_json, access_key_from_json),
    "channels": ("channels", channel_to_json, channel_from_json),
    "engine_instances": (
        "engine_instances",
        engine_instance_to_json,
        engine_instance_from_json,
    ),
    "engine_manifests": (
        "engine_manifests",
        manifest_to_json,
        manifest_from_json,
    ),
    "evaluation_instances": (
        "evaluation_instances",
        evaluation_instance_to_json,
        evaluation_instance_from_json,
    ),
}


class ReplicatedStoreClient:
    """Fan-out client over N store-server peers (see module docstring).

    DAO accessors hand out replicated wrappers; ``Storage`` binds them
    through the ``replicated`` backend spec exactly like any other
    source type, so the event server, trainer, and engine servers adopt
    replication by configuration alone.
    """

    def __init__(self, config: dict):
        urls = [
            u.strip()
            for u in str(config.get("URLS", "")).split(",")
            if u.strip()
        ]
        if not urls:
            raise StorageError(
                "replicated source needs PIO_STORAGE_SOURCES_<NAME>_URLS "
                "(comma-separated store-server base URLs)"
            )
        self.peers = [Peer(u, config) for u in urls]
        n = len(self.peers)
        default_w = n // 2 + 1
        try:
            self.w = int(config.get("W", default_w))
        except ValueError as e:
            raise StorageError(
                f"replicated W not an int: {config.get('W')!r}"
            ) from e
        if not 1 <= self.w <= n:
            raise StorageError(
                f"replicated W={self.w} out of range for {n} peer(s)"
            )
        base = config.get("HINT_DIR") or os.path.join(
            os.environ.get(
                "PIO_FS_BASEDIR",
                os.path.join(os.path.expanduser("~"), ".piotpu"),
            ),
            "replication_hints",
        )
        try:
            limit = int(config.get("HINT_LIMIT", _DEFAULT_HINT_LIMIT))
        except ValueError as e:
            raise StorageError(
                f"replicated HINT_LIMIT not an int: "
                f"{config.get('HINT_LIMIT')!r}"
            ) from e
        self.hints = {p.name: HintQueue(base, p.name, limit) for p in self.peers}
        #: write sequencing: one writer identity per client process,
        #: one monotonic counter per peer
        self.writer_id = uuid.uuid4().hex[:12]
        self._seq: dict[str, int] = {p.name: 0 for p in self.peers}
        self._seq_lock = threading.Lock()
        self._preferred = 0  # sticky failover-read index
        self._pref_lock = threading.Lock()
        registry = get_registry()
        self._lag_gauge, self._hints_gauge, self._repairs = (
            _register_metrics(registry)
        )
        for p in self.peers:
            self._hints_gauge.labels(p.name).set(
                self.hints[p.name].pending()
            )
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=n, thread_name_prefix="pio-repl"
        )
        self._stop = threading.Event()
        self._drainer = threading.Thread(
            target=self._hint_loop, daemon=True, name="pio-hint-drain"
        )
        self._drainer.start()
        self._dao_cache: dict[str, object] = {}
        logger.info(
            "replicated store: %d peer(s) %s, W=%d, hints under %s",
            n, [p.name for p in self.peers], self.w, base,
        )

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        self._drainer.join(timeout=2)
        self._pool.shutdown(wait=False)
        for p in self.peers:
            p.close()

    def dao(self, name: str):
        if name not in self._dao_cache:
            factory = {
                "apps": ReplicatedApps,
                "access_keys": ReplicatedAccessKeys,
                "channels": ReplicatedChannels,
                "engine_instances": ReplicatedEngineInstances,
                "engine_manifests": ReplicatedEngineManifests,
                "evaluation_instances": ReplicatedEvaluationInstances,
                "models": ReplicatedModels,
                "events": ReplicatedEvents,
            }[name]
            self._dao_cache[name] = factory(self)
        return self._dao_cache[name]

    def next_seq(self, peer: Peer) -> str:
        with self._seq_lock:
            self._seq[peer.name] += 1
            return f"{self.writer_id}:{self._seq[peer.name]}"

    def status(self) -> dict:
        """The client-side replication view (``replication_status``
        feeds it into a non-store server's /healthz)."""
        return {
            "role": "client",
            "n": len(self.peers),
            "w": self.w,
            "peers": [
                {
                    "url": p.url,
                    "breaker": p.breaker_state(),
                    "hintsPending": self.hints[p.name].pending(),
                    "hintsDropped": self.hints[p.name].dropped,
                }
                for p in self.peers
            ],
        }

    # -- quorum writes ----------------------------------------------------

    def quorum_write(
        self,
        op: str,
        fn: Callable[[Peer], Any],
        hint_payload: dict | Callable[[Peer], dict] | None = None,
    ) -> list[Any]:
        """Apply ``fn`` to every peer concurrently; require W acks.

        Returns per-peer results (None for a failed peer). With the
        quorum met, each failed peer gets a hint so the write reaches
        it on recovery; below quorum the write FAILS to the caller
        (whatever landed converges later via anti-entropy, but was
        never acked)."""
        futures = [
            (peer, self._pool.submit(fn, peer)) for peer in self.peers
        ]
        results: list[Any] = []
        failed: list[tuple[Peer, Exception]] = []
        for peer, fut in futures:
            try:
                results.append(fut.result())
            except StorageError as e:
                results.append(None)
                failed.append((peer, e))
        acks = len(self.peers) - len(failed)
        if acks < self.w:
            raise ReplicationError(
                f"{op}: only {acks}/{len(self.peers)} peers acked "
                f"(need W={self.w}); first error: {failed[0][1]}"
            )
        if failed and hint_payload is not None:
            for peer, err in failed:
                payload = (
                    hint_payload(peer)
                    if callable(hint_payload)
                    else hint_payload
                )
                self.add_hint(peer, payload)
                logger.warning(
                    "%s: peer %s missed the write (%s); hinted",
                    op, peer.name, err,
                )
        return results

    def add_hint(self, peer: Peer, payload: dict) -> None:
        queue = self.hints[peer.name]
        queue.append(payload)
        self._hints_gauge.labels(peer.name).set(queue.pending())
        _record(
            "store_hint_enqueued",
            f"hinted {payload.get('op', '?')} for down peer {peer.name}",
            severity=timeline_mod.WARN,
            peer=peer.name,
            pending=queue.pending(),
        )

    # -- failover reads ---------------------------------------------------

    def read_order(self) -> list[Peer]:
        with self._pref_lock:
            start = self._preferred
        n = len(self.peers)
        return [self.peers[(start + i) % n] for i in range(n)]

    def failover_read(
        self, op: str, fn: Callable[[Peer], Any], retry_none: bool = False
    ) -> Any:
        """Serve from the preferred peer, advancing (stickily) past
        dead ones. Raises the last error when every peer failed.

        ``retry_none`` (point-reads): a live peer answering None may
        simply have missed a quorum-acked write (hint still pending,
        anti-entropy not yet run) — e.g. an access key created a
        moment ago on W of N siblings. Only conclude not-found once
        every live peer agrees; sticky preference moves only past
        DEAD peers, so one stale replica cannot flap it."""
        last: Exception | None = None
        saw_none = False
        for i, peer in enumerate(self.read_order()):
            try:
                result = fn(peer)
            except StorageError as e:
                last = e
                continue
            if result is None and retry_none:
                saw_none = True
                continue
            if i and last is not None:
                with self._pref_lock:
                    self._preferred = self.peers.index(peer)
                _record(
                    "store_failover",
                    f"{op}: failed over to peer {peer.name} ({last})",
                    severity=timeline_mod.WARN,
                    peer=peer.name,
                )
            return result
        if saw_none:
            return None
        raise last if last is not None else StorageError(
            f"{op}: no peers configured"
        )

    # -- hinted-handoff drain ---------------------------------------------

    def _hint_loop(self) -> None:
        while not self._stop.wait(hint_interval()):
            for peer in self.peers:
                queue = self.hints[peer.name]
                if queue.pending() == 0:
                    continue
                if not peer.healthy():
                    continue
                try:
                    replayed = queue.drain(
                        lambda payload, p=peer: self._apply_hint(p, payload)
                    )
                except StorageError as e:
                    logger.info(
                        "hint drain to %s stopped: %s", peer.name, e
                    )
                    replayed = 0
                except Exception:  # noqa: BLE001 - the daemon drainer
                    # must outlive anything a single drain throws, or
                    # hinted handoff silently dies for the process
                    # lifetime while hints keep queueing
                    logger.exception("hint drain to %s failed", peer.name)
                    replayed = 0
                self._hints_gauge.labels(peer.name).set(queue.pending())
                if replayed:
                    self._repairs.labels("hinted_handoff").inc(replayed)
                    _record(
                        "store_hint_drained",
                        f"replayed {replayed} hinted write(s) to "
                        f"recovered peer {peer.name}",
                        peer=peer.name,
                        replayed=replayed,
                    )

    def _apply_hint(self, peer: Peer, payload: dict) -> None:
        op = payload.get("op")
        app_id = payload.get("appId")
        channel_id = payload.get("channelId")
        if op == "event":
            peer.events.insert(
                Event.from_json_dict(payload["event"]),
                app_id,
                channel_id,
                store_seq=payload.get("seq"),
                replay=True,
            )
        elif op == "event_batch":
            peer.events.insert_batch(
                [Event.from_json_dict(d) for d in payload["events"]],
                app_id,
                channel_id,
                store_seq=payload.get("seq"),
                replay=True,
            )
        elif op == "event_init":
            peer.events.init(app_id, channel_id)
        elif op == "event_remove":
            peer.events.remove(app_id, channel_id)
        elif op == "event_delete":
            peer.events.delete(payload["eventId"], app_id, channel_id)
        elif op == "model":
            peer.models.insert(
                Model(
                    id=payload["id"],
                    models=base64.b64decode(payload["b64"]),
                )
            )
        elif op == "model_delete":
            peer.models.delete(payload["id"])
        elif op == "meta":
            kind = payload["kind"]
            attr, _to_json, from_json = _META_KINDS[kind]
            dao = getattr(peer, attr)
            action = payload.get("action", "insert")
            if action == "delete":
                key = payload["key"]
                dao.delete(*key) if isinstance(key, list) else dao.delete(key)
            else:
                record = from_json(payload["record"])
                if kind == "engine_manifests":
                    dao.update(record, upsert=True)
                elif action == "update":
                    dao.update(record)
                else:
                    dao.insert(record)
        else:
            logger.warning("unknown hint op %r dropped", op)


def replication_status(storage) -> dict | None:
    """The replication view of a :class:`Storage` env, if any source is
    ``TYPE=replicated`` — what a non-store server (event server) merges
    into its ``/healthz``."""
    for name, (_spec, conf) in storage._specs.items():
        if conf.get("TYPE") == "replicated":
            return storage._client(name).status()
    return None


# --------------------------------------------------------------------------
# replicated DAOs
# --------------------------------------------------------------------------


class _ReplicatedBase:
    def __init__(self, rc: ReplicatedStoreClient):
        self._rc = rc


def _meta_hint(kind: str, action: str, record=None, key=None, to_json=None):
    payload: dict[str, Any] = {"op": "meta", "kind": kind, "action": action}
    if record is not None:
        payload["record"] = to_json(record)
    if key is not None:
        payload["key"] = key
    return payload


class ReplicatedApps(_ReplicatedBase, AppsBackend):
    def insert(self, app: App) -> int | None:
        # primary-first: one live peer assigns the id (or reports the
        # name conflict), then the CONCRETE record fans out — peers must
        # agree on ids, so auto-assignment can only happen once
        assigned = self._rc.failover_read(
            "apps.insert", lambda p: p.apps.insert(app)
        )
        if assigned is None:
            return None
        stamped = dataclasses.replace(app, id=assigned)

        def fan(peer: Peer):
            # a conflict on replay (record already there) is an ack
            peer.apps.insert(stamped)
            return True

        self._rc.quorum_write(
            "apps.insert",
            fan,
            _meta_hint("apps", "insert", stamped, to_json=app_to_json),
        )
        return assigned

    def get(self, app_id: int) -> App | None:
        return self._rc.failover_read(
            "apps.get", lambda p: p.apps.get(app_id), retry_none=True
        )

    def get_by_name(self, name: str) -> App | None:
        return self._rc.failover_read(
            "apps.get_by_name",
            lambda p: p.apps.get_by_name(name),
            retry_none=True,
        )

    def get_all(self) -> list[App]:
        return self._rc.failover_read(
            "apps.get_all", lambda p: p.apps.get_all()
        )

    def update(self, app: App) -> bool:
        out = self._rc.quorum_write(
            "apps.update",
            lambda p: p.apps.update(app),
            _meta_hint("apps", "update", app, to_json=app_to_json),
        )
        return any(bool(r) for r in out)

    def delete(self, app_id: int) -> bool:
        out = self._rc.quorum_write(
            "apps.delete",
            lambda p: p.apps.delete(app_id),
            _meta_hint("apps", "delete", key=app_id),
        )
        return any(bool(r) for r in out)


class ReplicatedAccessKeys(_ReplicatedBase, AccessKeysBackend):
    def insert(self, access_key: AccessKey) -> str | None:
        key = access_key.key or self.generate_key()
        stamped = dataclasses.replace(access_key, key=key)
        self._rc.quorum_write(
            "access_keys.insert",
            lambda p: p.access_keys.insert(stamped),
            _meta_hint(
                "access_keys", "insert", stamped, to_json=access_key_to_json
            ),
        )
        return key

    def get(self, key: str) -> AccessKey | None:
        return self._rc.failover_read(
            "access_keys.get",
            lambda p: p.access_keys.get(key),
            retry_none=True,
        )

    def get_all(self) -> list[AccessKey]:
        return self._rc.failover_read(
            "access_keys.get_all", lambda p: p.access_keys.get_all()
        )

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return self._rc.failover_read(
            "access_keys.get_by_app_id",
            lambda p: p.access_keys.get_by_app_id(app_id),
        )

    def update(self, access_key: AccessKey) -> bool:
        out = self._rc.quorum_write(
            "access_keys.update",
            lambda p: p.access_keys.update(access_key),
            _meta_hint(
                "access_keys", "update", access_key,
                to_json=access_key_to_json,
            ),
        )
        return any(bool(r) for r in out)

    def delete(self, key: str) -> bool:
        out = self._rc.quorum_write(
            "access_keys.delete",
            lambda p: p.access_keys.delete(key),
            _meta_hint("access_keys", "delete", key=key),
        )
        return any(bool(r) for r in out)


class ReplicatedChannels(_ReplicatedBase, ChannelsBackend):
    def insert(self, channel: Channel) -> int | None:
        assigned = self._rc.failover_read(
            "channels.insert", lambda p: p.channels.insert(channel)
        )
        if assigned is None:
            return None
        stamped = dataclasses.replace(channel, id=assigned)
        self._rc.quorum_write(
            "channels.insert",
            lambda p: p.channels.insert(stamped),
            _meta_hint(
                "channels", "insert", stamped, to_json=channel_to_json
            ),
        )
        return assigned

    def get(self, channel_id: int) -> Channel | None:
        return self._rc.failover_read(
            "channels.get",
            lambda p: p.channels.get(channel_id),
            retry_none=True,
        )

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return self._rc.failover_read(
            "channels.get_by_app_id",
            lambda p: p.channels.get_by_app_id(app_id),
        )

    def delete(self, channel_id: int) -> bool:
        out = self._rc.quorum_write(
            "channels.delete",
            lambda p: p.channels.delete(channel_id),
            _meta_hint("channels", "delete", key=channel_id),
        )
        return any(bool(r) for r in out)


class ReplicatedEngineManifests(_ReplicatedBase, EngineManifestsBackend):
    def insert(self, manifest: EngineManifest) -> None:
        self._rc.quorum_write(
            "engine_manifests.insert",
            lambda p: p.engine_manifests.insert(manifest),
            _meta_hint(
                "engine_manifests", "insert", manifest,
                to_json=manifest_to_json,
            ),
        )

    def get(self, manifest_id: str, version: str) -> EngineManifest | None:
        return self._rc.failover_read(
            "engine_manifests.get",
            lambda p: p.engine_manifests.get(manifest_id, version),
            retry_none=True,
        )

    def get_all(self) -> list[EngineManifest]:
        return self._rc.failover_read(
            "engine_manifests.get_all",
            lambda p: p.engine_manifests.get_all(),
        )

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        self._rc.quorum_write(
            "engine_manifests.update",
            lambda p: p.engine_manifests.update(manifest, upsert=upsert),
            _meta_hint(
                "engine_manifests", "update", manifest,
                to_json=manifest_to_json,
            ),
        )

    def delete(self, manifest_id: str, version: str) -> bool:
        out = self._rc.quorum_write(
            "engine_manifests.delete",
            lambda p: p.engine_manifests.delete(manifest_id, version),
            _meta_hint(
                "engine_manifests", "delete", key=[manifest_id, version]
            ),
        )
        return any(bool(r) for r in out)


class ReplicatedEngineInstances(_ReplicatedBase, EngineInstancesBackend):
    def insert(self, instance: EngineInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        stamped = dataclasses.replace(instance, id=iid)
        self._rc.quorum_write(
            "engine_instances.insert",
            lambda p: p.engine_instances.insert(stamped),
            _meta_hint(
                "engine_instances", "insert", stamped,
                to_json=engine_instance_to_json,
            ),
        )
        return iid

    def get(self, instance_id: str) -> EngineInstance | None:
        return self._rc.failover_read(
            "engine_instances.get",
            lambda p: p.engine_instances.get(instance_id),
            retry_none=True,
        )

    def get_all(self) -> list[EngineInstance]:
        return self._rc.failover_read(
            "engine_instances.get_all",
            lambda p: p.engine_instances.get_all(),
        )

    def _merged_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        """Union across ALL live peers, newest first — the engine
        server's reload path must find a generation published during a
        peer outage no matter which peer it asks first."""
        by_id: dict[str, EngineInstance] = {}
        live = 0
        for peer in self._rc.read_order():
            try:
                rows = peer.engine_instances.get_completed(
                    engine_id, engine_version, engine_variant
                )
            except StorageError:
                continue
            live += 1
            for row in rows:
                by_id.setdefault(row.id, row)
        if live == 0:
            raise StorageError(
                "engine_instances.get_completed: no live peers"
            )
        return sorted(
            by_id.values(), key=lambda i: i.start_time, reverse=True
        )

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        return self._merged_completed(
            engine_id, engine_version, engine_variant
        )

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        merged = self._merged_completed(
            engine_id, engine_version, engine_variant
        )
        return merged[0] if merged else None

    def update(self, instance: EngineInstance) -> bool:
        out = self._rc.quorum_write(
            "engine_instances.update",
            lambda p: p.engine_instances.update(instance),
            _meta_hint(
                "engine_instances", "update", instance,
                to_json=engine_instance_to_json,
            ),
        )
        return any(bool(r) for r in out)

    def delete(self, instance_id: str) -> bool:
        out = self._rc.quorum_write(
            "engine_instances.delete",
            lambda p: p.engine_instances.delete(instance_id),
            _meta_hint("engine_instances", "delete", key=instance_id),
        )
        return any(bool(r) for r in out)


class ReplicatedEvaluationInstances(
    _ReplicatedBase, EvaluationInstancesBackend
):
    def insert(self, instance: EvaluationInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        stamped = dataclasses.replace(instance, id=iid)
        self._rc.quorum_write(
            "evaluation_instances.insert",
            lambda p: p.evaluation_instances.insert(stamped),
            _meta_hint(
                "evaluation_instances", "insert", stamped,
                to_json=evaluation_instance_to_json,
            ),
        )
        return iid

    def get(self, instance_id: str) -> EvaluationInstance | None:
        return self._rc.failover_read(
            "evaluation_instances.get",
            lambda p: p.evaluation_instances.get(instance_id),
            retry_none=True,
        )

    def get_all(self) -> list[EvaluationInstance]:
        return self._rc.failover_read(
            "evaluation_instances.get_all",
            lambda p: p.evaluation_instances.get_all(),
        )

    def get_completed(self) -> list[EvaluationInstance]:
        return self._rc.failover_read(
            "evaluation_instances.get_completed",
            lambda p: p.evaluation_instances.get_completed(),
        )

    def update(self, instance: EvaluationInstance) -> bool:
        out = self._rc.quorum_write(
            "evaluation_instances.update",
            lambda p: p.evaluation_instances.update(instance),
            _meta_hint(
                "evaluation_instances", "update", instance,
                to_json=evaluation_instance_to_json,
            ),
        )
        return any(bool(r) for r in out)

    def delete(self, instance_id: str) -> bool:
        out = self._rc.quorum_write(
            "evaluation_instances.delete",
            lambda p: p.evaluation_instances.delete(instance_id),
            _meta_hint("evaluation_instances", "delete", key=instance_id),
        )
        return any(bool(r) for r in out)


class ReplicatedModels(_ReplicatedBase, ModelsBackend):
    """Quorum blob writes + manifest-verified failover reads.

    The trainer's generation publish
    (``core/persistence.publish_generation``) writes the blob, then the
    manifest. Both inserts go through :meth:`insert`, which raises
    below quorum — so the manifest COMMIT only happens once the blob is
    quorum-durable, and a generation can never become loadable on peers
    that would then fail to serve its artifact.
    """

    def insert(self, model: Model) -> None:
        self._rc.quorum_write(
            "models.insert",
            lambda p: p.models.insert(model),
            lambda peer: {
                "op": "model",
                "id": model.id,
                "b64": base64.b64encode(model.models).decode("ascii"),
            },
        )

    def _manifest_spec(self, peer: Peer, model_id: str) -> dict | None:
        """The manifest's artifact entry for ``model_id`` on ``peer``,
        or None when the blob is legacy/unmanifested."""
        if model_id.endswith(_MANIFEST_SUFFIX):
            return None
        try:
            record = peer.models.get(model_id + _MANIFEST_SUFFIX)
        except StorageError:
            return None
        if record is None:
            return None
        try:
            manifest = json.loads(record.models.decode("utf-8"))
            for art in manifest.get("artifacts", ()):
                if art.get("id") == model_id:
                    return art
        except (ValueError, UnicodeDecodeError):
            return None
        return None

    @staticmethod
    def _verify(blob: bytes, spec: dict | None) -> bool:
        if spec is None:
            return True
        if len(blob) != spec.get("bytes"):
            return False
        return hashlib.sha256(blob).hexdigest() == spec.get("sha256")

    def get(self, model_id: str) -> Model | None:
        """Failover read with read-repair: serve the first peer whose
        blob verifies against its generation manifest; peers found
        stale (missing) or corrupt (checksum mismatch) are backfilled
        from the verified copy."""
        stale: list[Peer] = []
        found: Model | None = None
        errors: Exception | None = None
        source: Peer | None = None
        for peer in self._rc.read_order():
            try:
                record = peer.models.get(model_id)
            except StorageError as e:
                errors = e
                continue
            if record is None:
                stale.append(peer)
                continue
            spec = self._manifest_spec(peer, model_id)
            if not self._verify(record.models, spec):
                self._rc._repairs.labels("corrupt_detected").inc()
                _record(
                    "store_read_corrupt",
                    f"model {model_id} on {peer.name} fails its "
                    "manifest checksum; trying next peer",
                    severity=timeline_mod.WARN,
                    peer=peer.name,
                )
                stale.append(peer)
                continue
            found = record
            source = peer
            break
        if found is None:
            if errors is not None and not stale:
                raise errors
            return None
        for peer in stale:
            try:
                peer.models.insert(found)
            except StorageError:
                continue
            self._rc._repairs.labels("read_repair").inc()
            _record(
                "store_read_repair",
                f"backfilled model {model_id} to stale peer "
                f"{peer.name} from {source.name}",
                peer=peer.name,
            )
        return found

    def delete(self, model_id: str) -> bool:
        out = self._rc.quorum_write(
            "models.delete",
            lambda p: p.models.delete(model_id),
            {"op": "model_delete", "id": model_id},
        )
        return any(bool(r) for r in out)

    def list_ids(self) -> list[str] | None:
        return self._rc.failover_read(
            "models.list_ids", lambda p: p.models.list_ids()
        )


class ReplicatedEvents(_ReplicatedBase, EventsBackend):
    """Quorum event ingest (the ``zero ack'd-write loss`` contract).

    Events are id-stamped BEFORE the fan-out so every peer stores the
    same identity; a peer-level send failure retries once with the same
    ``X-PIO-Store-Seq`` token (the server dedupes the replay), then
    falls to hinted handoff if the quorum still holds without it.
    """

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        out = self._rc.quorum_write(
            "events.init",
            lambda p: p.events.init(app_id, channel_id),
            {"op": "event_init", "appId": app_id, "channelId": channel_id},
        )
        return any(bool(r) for r in out)

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        out = self._rc.quorum_write(
            "events.remove",
            lambda p: p.events.remove(app_id, channel_id),
            {"op": "event_remove", "appId": app_id, "channelId": channel_id},
        )
        return any(bool(r) for r in out)

    def close(self) -> None:
        pass  # peers are owned by the client; Storage closes it

    def _insert_one_on(
        self, peer: Peer, stamped: Event, app_id, channel_id
    ) -> str:
        seq = self._rc.next_seq(peer)
        try:
            return peer.events.insert(
                stamped, app_id, channel_id, store_seq=seq
            )
        except StorageError:
            # one replay with the SAME token: if the first send
            # committed before the connection died, the server answers
            # from its dedupe cache (or skips the duplicate id)
            return peer.events.insert(
                stamped, app_id, channel_id, store_seq=seq
            )

    def insert(
        self, event: Event, app_id: int, channel_id: int | None = None
    ) -> str:
        stamped = event.with_id(event.event_id)
        self._rc.quorum_write(
            "events.insert",
            lambda p: self._insert_one_on(p, stamped, app_id, channel_id),
            lambda peer: {
                "op": "event",
                "appId": app_id,
                "channelId": channel_id,
                "event": stamped.to_json_dict(),
                "seq": self._rc.next_seq(peer),
            },
        )
        return stamped.event_id

    def insert_batch(
        self,
        events,
        app_id: int,
        channel_id: int | None = None,
    ) -> list[str]:
        if not events:
            return []
        stamped = [e.with_id(e.event_id) for e in events]
        ids = [e.event_id for e in stamped]
        rc = self._rc

        def attempt(peer: Peer):
            seq = rc.next_seq(peer)
            try:
                acked = peer.events.insert_batch(
                    stamped, app_id, channel_id, store_seq=seq
                )
                return set(acked), None, seq
            except PartialBatchError as e:
                # the peer ANSWERED: its durable prefix is exact
                return set(e.inserted_ids), "partial", seq
            except StorageError:
                try:
                    acked = peer.events.insert_batch(
                        stamped, app_id, channel_id, store_seq=seq
                    )
                    return set(acked), None, seq
                except PartialBatchError as e:
                    return set(e.inserted_ids), "partial", seq
                except StorageError:
                    return set(), "fail", seq

        futures = [
            (peer, rc._pool.submit(attempt, peer)) for peer in rc.peers
        ]
        per_peer: list[tuple[Peer, set, str | None, str]] = []
        for peer, fut in futures:
            acked, state, seq = fut.result()
            per_peer.append((peer, acked, state, seq))

        # durable prefix: an event is ack'd iff >= W peers hold it, and
        # the batch contract only acks an unbroken prefix
        durable: list[str] = []
        for event_id in ids:
            votes = sum(
                1 for _p, acked, _s, _q in per_peer if event_id in acked
            )
            if votes >= rc.w:
                durable.append(event_id)
            else:
                break

        # hints carry only the DURABLE prefix: an event that never
        # reached quorum was never acked to the caller
        # (PartialBatchError below), so replaying it later would
        # resurrect a write the caller believes failed — and a caller
        # retry of the suffix (fresh UUIDs) would then logically
        # duplicate it. The un-acked suffix converges via anti-entropy
        # only, exactly the below-quorum contract of quorum_write. A
        # fully-failed peer keeps its original seq token (an ambiguous
        # torn-but-committed send dedupes server-side).
        durable_set = set(durable)
        durable_events = [e for e in stamped if e.event_id in durable_set]
        for peer, acked, state, seq in per_peer:
            missing = [
                e for e in durable_events if e.event_id not in acked
            ]
            if not missing:
                continue
            payload = {
                "op": "event_batch",
                "appId": app_id,
                "channelId": channel_id,
                "events": [e.to_json_dict() for e in missing],
            }
            if state == "fail":
                payload["seq"] = seq
            rc.add_hint(peer, payload)

        if len(durable) < len(ids):
            raise PartialBatchError(
                f"only {len(durable)}/{len(ids)} events reached the "
                f"W={rc.w} quorum",
                durable,
            )
        return ids

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        return self._rc.failover_read(
            "events.get",
            lambda p: p.events.get(event_id, app_id, channel_id),
            retry_none=True,
        )

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        out = self._rc.quorum_write(
            "events.delete",
            lambda p: p.events.delete(event_id, app_id, channel_id),
            {
                "op": "event_delete",
                "appId": app_id,
                "channelId": channel_id,
                "eventId": event_id,
            },
        )
        return any(bool(r) for r in out)

    def find(self, app_id: int, channel_id: int | None = None, **kw):
        rows = self._rc.failover_read(
            "events.find",
            lambda p: list(p.events.find(app_id, channel_id, **kw)),
        )
        yield from rows


# --------------------------------------------------------------------------
# anti-entropy (runs inside each store server)
# --------------------------------------------------------------------------


class AntiEntropyLoop:
    """Pull-based convergence: each store server, given its replica-set
    siblings (``--peer``), periodically asks every peer what it has and
    pulls anything missing locally — metadata by id, events by
    watermark comparison, model blobs by id-set diff (blobs before
    manifests, so a pulled generation commits atomically here too).
    A node restarted empty (or SIGKILLed mid-batch) converges without
    operator action; the repair is visible in the timeline and the
    ``pio_store_repair_total`` counter.
    """

    def __init__(
        self,
        storage,
        peers: Iterable[str],
        role: str = "replica",
        registry=None,
        timeline=None,
        key: str | None = None,
        interval: float | None = None,
        insert_lock: threading.Lock | None = None,
        watermarks=None,
    ):
        self._storage = storage
        conf = {"KEY": key} if key else {}
        self.peers = [Peer(u, conf) for u in peers]
        self.role = role
        self.interval = interval or sync_interval()
        registry = registry or get_registry()
        self._lag_gauge, self._hints_gauge, self._repairs = (
            _register_metrics(registry)
        )
        self._timeline = timeline
        #: shared with the store server's event-insert routes: the pull
        #: below and the routes are both check-then-insert against an
        #: append-only log, and an unserialized interleaving (e.g. a
        #: hinted-handoff replay racing the pull after a restart) lands
        #: duplicate records no later repair can remove
        self.insert_lock = insert_lock or threading.Lock()
        #: the store server's incremental EventWatermarkCache (when
        #: this loop runs inside one) — keeps the local side of every
        #: watermark comparison O(1) instead of a full log scan per
        #: round, and folds pulled events in so it stays exact. A
        #: standalone loop (tests, tooling) leaves it None and scans.
        self._watermarks = watermarks
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._status_lock = threading.Lock()
        self._peer_status: dict[str, dict] = {
            p.name: {"url": p.url, "lagSeconds": None, "lastSync": None,
                     "error": None}
            for p in self.peers
        }
        self._last_sync: float | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pio-anti-entropy"
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        for p in self.peers:
            p.close()

    def status(self) -> dict:
        """The ``/healthz`` replication payload for this node."""
        with self._status_lock:
            peers = [dict(v) for v in self._peer_status.values()]
            last = self._last_sync
        return {
            "role": self.role,
            "peers": peers,
            "lastSync": last,
            "syncInterval": self.interval,
        }

    # -- sync -------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 - loop must survive anything
                logger.exception("anti-entropy round failed")

    def sync_once(self, horizon: float | None = None) -> dict:
        """One full round against every peer; returns pull counts.

        ``horizon`` (seconds; default ``max(1, interval)``) excludes
        events created within the last that-many seconds from the pull:
        a write currently fanning out to this node would otherwise race
        the pull of its own copy from a faster sibling and land twice.
        Anything the horizon defers is picked up one round later. Pass
        ``0`` to pull everything (deterministic tests on quiesced
        stores)."""
        if horizon is None:
            horizon = max(1.0, self.interval)
        totals = {"metadata": 0, "events": 0, "models": 0}
        for peer in self.peers:
            if self._stop.is_set():
                break
            try:
                pulled = self._sync_peer(peer, horizon)
            except StorageError as e:
                self._repairs.labels("error").inc()
                with self._status_lock:
                    self._peer_status[peer.name]["error"] = str(e)
                continue
            for k in totals:
                totals[k] += pulled[k]
            with self._status_lock:
                self._peer_status[peer.name].update(
                    lastSync=time.time(), error=None
                )
        with self._status_lock:
            self._last_sync = time.time()
        total = sum(totals.values())
        if total and self._timeline is not None:
            self._timeline.record(
                "store_antientropy",
                f"anti-entropy pulled {totals['events']} event(s), "
                f"{totals['models']} model blob(s), "
                f"{totals['metadata']} metadata record(s) from peers",
                **totals,
            )
        return totals

    def _sync_peer(self, peer: Peer, horizon: float = 0.0) -> dict:
        pulled = {"metadata": 0, "events": 0, "models": 0}
        pulled["metadata"] += self._sync_metadata(peer)
        pulled["events"] += self._sync_events(peer, horizon)
        pulled["models"] += self._sync_models(peer)
        if pulled["metadata"]:
            self._repairs.labels("metadata").inc(pulled["metadata"])
        if pulled["events"]:
            self._repairs.labels("events").inc(pulled["events"])
        if pulled["models"]:
            self._repairs.labels("models").inc(pulled["models"])
        return pulled

    # metadata: pull records the peer has that we don't, keyed per kind
    def _sync_metadata(self, peer: Peer) -> int:
        s = self._storage
        pulled = 0
        pulled += self._pull_missing(
            peer.apps.get_all(),
            s.get_meta_data_apps(),
            key=lambda a: a.id,
        )
        pulled += self._pull_missing(
            peer.access_keys.get_all(),
            s.get_meta_data_access_keys(),
            key=lambda k: k.key,
        )
        local_channels = s.get_meta_data_channels()
        their_channels = []
        for app in s.get_meta_data_apps().get_all():
            their_channels.extend(peer.channels.get_by_app_id(app.id))
        mine = {
            c.id
            for app in s.get_meta_data_apps().get_all()
            for c in local_channels.get_by_app_id(app.id)
        }
        for chan in their_channels:
            if chan.id not in mine:
                local_channels.insert(chan)
                pulled += 1
        pulled += self._pull_missing(
            peer.engine_instances.get_all(),
            s.get_meta_data_engine_instances(),
            key=lambda i: i.id,
        )
        pulled += self._pull_missing(
            peer.evaluation_instances.get_all(),
            s.get_meta_data_evaluation_instances(),
            key=lambda i: i.id,
        )
        local_manifests = s.get_meta_data_engine_manifests()
        mine_m = {(m.id, m.version) for m in local_manifests.get_all()}
        for m in peer.engine_manifests.get_all():
            if (m.id, m.version) not in mine_m:
                local_manifests.insert(m)
                pulled += 1
        return pulled

    @staticmethod
    def _pull_missing(theirs, local_dao, key) -> int:
        mine = {key(r) for r in local_dao.get_all()}
        pulled = 0
        for record in theirs:
            if key(record) not in mine:
                local_dao.insert(record)
                pulled += 1
        return pulled

    # events: watermark comparison per (app, channel), full pull only
    # on divergence; inserts are id-checked so replays can't duplicate
    def _event_coords(self) -> list[tuple[int, int | None]]:
        s = self._storage
        coords: list[tuple[int, int | None]] = []
        channels = s.get_meta_data_channels()
        for app in s.get_meta_data_apps().get_all():
            coords.append((app.id, None))
            for chan in channels.get_by_app_id(app.id):
                coords.append((app.id, chan.id))
        return coords

    def _local_watermark(
        self, app_id: int, channel_id: int | None
    ) -> tuple[str, Any]:
        dao = self._storage.get_events()
        if self._watermarks is not None:
            summary = self._watermarks.summary(app_id, channel_id, dao)
            return summary["checksum"], summary["latest"]

        from predictionio_tpu.serving.store_server import event_set_checksum

        latest = None

        def _ids():
            nonlocal latest
            for e in dao.find(app_id, channel_id):
                if latest is None or e.creation_time > latest:
                    latest = e.creation_time
                yield e.event_id

        checksum = event_set_checksum(_ids())
        return checksum, latest

    def _sync_events(self, peer: Peer, horizon: float = 0.0) -> int:
        import datetime as _dt

        dao = self._storage.get_events()
        cutoff = (
            _dt.datetime.now(_dt.timezone.utc)
            - _dt.timedelta(seconds=horizon)
        )
        pulled = 0
        worst_lag = 0.0
        for app_id, channel_id in self._event_coords():
            try:
                theirs = peer.events.watermark(app_id, channel_id)
            except StorageError:
                continue  # peer may not have this app's log yet
            mine_checksum, mine_latest = self._local_watermark(
                app_id, channel_id
            )
            their_latest = theirs.get("latest")
            if their_latest and mine_latest is not None:
                try:
                    their_dt = _dt.datetime.fromisoformat(their_latest)
                    worst_lag = max(
                        worst_lag,
                        (mine_latest - their_dt).total_seconds(),
                    )
                except ValueError:
                    pass
            if theirs.get("checksum") == mine_checksum:
                continue
            for event in peer.events.find(app_id, channel_id):
                if horizon and event.creation_time > cutoff:
                    # too fresh: its own fan-out write may still be in
                    # flight toward us — defer to the next round rather
                    # than race it into a duplicate append
                    continue
                with self.insert_lock:
                    if dao.get(
                        event.event_id, app_id, channel_id
                    ) is None:
                        dao.insert(event, app_id, channel_id)
                        if self._watermarks is not None:
                            self._watermarks.record_insert_locked(
                                app_id, channel_id, event
                            )
                        pulled += 1
        # lag: how far the PEER trails us (what /healthz reports as
        # this node's view of its replica set)
        self._lag_gauge.labels(peer.name).set(max(0.0, worst_lag))
        with self._status_lock:
            self._peer_status[peer.name]["lagSeconds"] = max(0.0, worst_lag)
        return pulled

    # models: id-set diff, blobs before manifests so the manifest stays
    # the commit point; pulled blobs verify against the manifest they
    # arrive with before anything becomes loadable
    def _sync_models(self, peer: Peer) -> int:
        local = self._storage.get_model_data_models()
        mine = local.list_ids()
        if mine is None:
            return 0
        try:
            theirs = peer.models.list_ids()
        except StorageError:
            return 0
        if theirs is None:
            return 0
        missing = [i for i in theirs if i not in set(mine)]
        if not missing:
            return 0
        blobs = [i for i in missing if not i.endswith(_MANIFEST_SUFFIX)]
        manifests = [i for i in missing if i.endswith(_MANIFEST_SUFFIX)]
        pulled = 0
        for model_id in blobs:
            record = peer.models.get(model_id)
            if record is not None:
                local.insert(record)
                pulled += 1
        for manifest_blob_id in manifests:
            record = peer.models.get(manifest_blob_id)
            if record is None:
                continue
            if not self._manifest_artifacts_ok(local, record):
                # commit point discipline: never land a manifest whose
                # artifacts aren't verified-present locally
                self._repairs.labels("manifest_deferred").inc()
                continue
            local.insert(record)
            pulled += 1
        return pulled

    @staticmethod
    def _manifest_artifacts_ok(local, manifest_record: Model) -> bool:
        try:
            manifest = json.loads(manifest_record.models.decode("utf-8"))
            artifacts = manifest.get("artifacts", ())
        except (ValueError, UnicodeDecodeError):
            return False
        for art in artifacts:
            blob = local.get(art.get("id", ""))
            if blob is None:
                return False
            if len(blob.models) != art.get("bytes"):
                return False
            if hashlib.sha256(blob.models).hexdigest() != art.get("sha256"):
                return False
        return True
