"""SARIF 2.1.0 renderer for ``pio-tpu lint --format sarif``.

One ``run`` with the full rule catalog as ``tool.driver.rules`` (rule
metadata + fix hint as the rule's help text) and one ``result`` per NEW
finding. CI uploads the file with ``github/codeql-action/upload-sarif``
so findings land in the repository's Security → Code scanning tab,
alongside the inline ``--format github`` annotations.

``partialFingerprints`` carries the same line-number-free fingerprint
the baseline uses (rule | path | enclosing qualname | normalized source
line), so code-scanning alert identity survives unrelated edits above
a finding — exactly the property the baseline format was designed for.

Unanalyzable files are reported as tool ``notifications`` with level
``error`` (they fail the gate but have no rule or precise location).
"""

from __future__ import annotations

import json

from predictionio_tpu.analysis.model import RULES, Finding


def _rule_ids() -> list[str]:
    return list(RULES)


def _sarif_rules() -> list[dict]:
    out = []
    for rule in RULES.values():
        out.append(
            {
                "id": rule.id,
                "name": rule.id.replace("-", " ").title().replace(" ", ""),
                "shortDescription": {"text": rule.summary},
                "fullDescription": {"text": rule.summary},
                "help": {
                    "text": (
                        f"fix: {rule.hint} "
                        "(rationale + examples: docs/static_analysis.md)"
                    )
                },
                "defaultConfiguration": {"level": "error"},
            }
        )
    return out


def _sarif_result(
    f: Finding, rule_index: dict[str, int], context_unique: bool
) -> dict:
    fp_rule, fp_path, fp_ctx, fp_src = f.fingerprint()
    fingerprints = {
        "pioLint/v1": f"{fp_rule}|{fp_path}|{fp_ctx}|{fp_src}",
    }
    if context_unique:
        # path-free identity: survives a file RENAME on top of the
        # line-number freedom above (code scanning matches alerts on
        # any shared fingerprint key, so a rename plus edits above the
        # site keeps the alert instead of closing and reopening it
        # under a new identity). Omitted when two findings in
        # DIFFERENT files share the triple (copy-paste twins): a
        # shared key would conflate two distinct alerts, and fixing
        # one would silently close the other.
        fingerprints["pioLint/contextV1"] = (
            f"{fp_rule}|{fp_ctx}|{fp_src}"
        )
    return {
        "ruleId": f.rule,
        "ruleIndex": rule_index[f.rule],
        "level": "error",
        "message": {"text": f"{f.message} — fix: {f.hint}"},
        "locations": [
            {
                "physicalLocation": {
                    # repo-relative URI with no uriBaseId: the upload
                    # action resolves it against the checkout root
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": f.line,
                        # SARIF columns are 1-based; Finding.col is 0-based
                        "startColumn": f.col + 1,
                        "snippet": {"text": f.source},
                    },
                },
                "logicalLocations": (
                    [{"fullyQualifiedName": f.context, "kind": "function"}]
                    if f.context
                    else []
                ),
            }
        ],
        "partialFingerprints": fingerprints,
    }


def render_sarif(result, tool_version: str) -> str:
    """SARIF 2.1.0 JSON for a :class:`LintResult` (new findings only:
    the shipped baseline is empty by policy, and a baselined finding is
    accepted debt, not an alert)."""
    rule_index = {rid: i for i, rid in enumerate(_rule_ids())}
    triple_counts: dict[tuple, int] = {}
    for f in result.new:
        fp_rule, _p, fp_ctx, fp_src = f.fingerprint()
        key = (fp_rule, fp_ctx, fp_src)
        triple_counts[key] = triple_counts.get(key, 0) + 1
    notifications = [
        {
            "level": "error",
            "message": {"text": err},
            "descriptor": {"id": "pio-lint/unanalyzable"},
        }
        for err in result.errors
    ]
    run = {
        "tool": {
            "driver": {
                "name": "pio-tpu-lint",
                "version": tool_version,
                "semanticVersion": tool_version,
                "rules": _sarif_rules(),
            }
        },
        "columnKind": "utf16CodeUnits",
        "results": [
            _sarif_result(
                f,
                rule_index,
                context_unique=triple_counts[
                    (f.fingerprint()[0],) + f.fingerprint()[2:]
                ] == 1,
            )
            for f in result.new
        ],
        "invocations": [
            {
                "executionSuccessful": not result.errors,
                "toolExecutionNotifications": notifications,
            }
        ],
    }
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [run],
    }
    return json.dumps(doc, indent=2, sort_keys=False)
