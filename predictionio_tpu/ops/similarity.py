"""Scoring / similarity kernels for serving.

Replaces the reference's per-query RDD predict (ALSAlgorithm.predict:
``productFeatures.lookup`` + cosine ``collect`` — a Spark job per query,
the serving anti-pattern SURVEY.md §3.2 flags) with pre-compiled dense
scoring: one [B, k] × [k, I] matmul + ``lax.top_k``. The same kernels
serve the recommendation template (dot-product scores) and the
similar-product template (cosine over item factors,
examples/scala-parallel-similarproduct/multi/.../ALSAlgorithm.scala).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def l2_normalize(x: jax.Array, eps: float = 1e-9) -> jax.Array:
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)


@partial(jax.jit, static_argnames=("num",))
def top_k_dot(
    queries: jax.Array,      # [B, k]
    items: jax.Array,        # [I, k]
    num: int,
    mask: jax.Array | None = None,  # [B, I] True = exclude
) -> tuple[jax.Array, jax.Array]:
    """Top-``num`` items by dot product. Returns (scores, indices) [B, num]."""
    scores = queries @ items.T  # [B, I] — MXU
    if mask is not None:
        scores = jnp.where(mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, num)


@partial(jax.jit, static_argnames=("num",))
def top_k_cosine(
    queries: jax.Array,
    items: jax.Array,
    num: int,
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-``num`` by cosine similarity (similar-product scoring)."""
    return top_k_dot(
        l2_normalize(queries), l2_normalize(items), num, mask
    )
