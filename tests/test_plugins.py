"""Plugin framework tests — input/output blockers + sniffers.

Reference behavior under test: EventServerPlugin (inputblocker rejects
pre-storage, inputsniffer observes async), EngineServerPlugin
(outputblocker folds over the prediction, CreateServer.scala:603-606),
``/plugins.json`` listings, and ``PIO_PLUGINS`` env loading (the
ServiceLoader replacement).
"""

import json
import time
import urllib.request

import pytest

from predictionio_tpu.data.storage import Storage
from predictionio_tpu.serving.event_server import EventServer
from predictionio_tpu.serving.http import HTTPServer
from predictionio_tpu.serving.plugins import (
    INPUT_BLOCKER,
    INPUT_SNIFFER,
    OUTPUT_BLOCKER,
    EngineServerPlugin,
    EventServerPlugin,
    PluginContext,
    PluginRejection,
    load_plugin_spec,
    plugins_from_env,
)

# -- fixtures ---------------------------------------------------------------


class RejectBuyBlocker(EventServerPlugin):
    plugin_name = "reject-buy"
    plugin_description = "rejects buy events"
    plugin_type = INPUT_BLOCKER

    def process(self, event_json, app_id, channel_id):
        if event_json["event"] == "buy":
            raise PluginRejection("no buying allowed")


class RecordingSniffer(EventServerPlugin):
    plugin_name = "recorder"
    plugin_type = INPUT_SNIFFER

    def __init__(self):
        self.seen = []

    def process(self, event_json, app_id, channel_id):
        self.seen.append((event_json["event"], app_id))

    def handle_rest(self, path, query):
        return {"seen": len(self.seen), "path": path}


class UppercasePlugin(EngineServerPlugin):
    plugin_name = "upper"
    plugin_type = OUTPUT_BLOCKER

    def process(self, engine_info, query, prediction):
        return {**prediction, "label": prediction["label"].upper()}


SAMPLE_PLUGIN = RecordingSniffer()  # module-level for spec loading


@pytest.fixture
def server(sqlite_storage: Storage):
    from predictionio_tpu.data.storage import AccessKey, App

    apps = sqlite_storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="pluginapp"))
    sqlite_storage.get_events().init(app_id)
    key = sqlite_storage.get_meta_data_access_keys().insert(
        AccessKey(key="pkey", appid=app_id)
    )
    sniffer = RecordingSniffer()
    ctx = PluginContext(
        [RejectBuyBlocker(), sniffer], load_env=False
    )
    es = EventServer(storage=sqlite_storage, plugins=ctx)
    http = HTTPServer(es.router, host="127.0.0.1", port=0)
    http.start()
    yield http, key, sniffer
    http.shutdown()
    ctx.close()


def _post(port, path, payload, expect_error=False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        if not expect_error:
            raise
        return e.code, json.loads(e.read())


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}"
    ) as r:
        return r.status, json.loads(r.read())


# -- event server plugin behavior -------------------------------------------


def test_input_blocker_rejects(server):
    http, key, _ = server
    status, body = _post(
        http.port,
        f"/events.json?accessKey={key}",
        {"event": "buy", "entityType": "user", "entityId": "u1"},
        expect_error=True,
    )
    assert status == 403
    assert "no buying" in body["message"]


def test_input_blocker_passes_other_events(server):
    http, key, sniffer = server
    status, body = _post(
        http.port,
        f"/events.json?accessKey={key}",
        {"event": "view", "entityType": "user", "entityId": "u1"},
    )
    assert status == 201 and body["eventId"]
    # sniffer sees the accepted event (async)
    deadline = time.time() + 5
    while not sniffer.seen and time.time() < deadline:
        time.sleep(0.01)
    assert sniffer.seen and sniffer.seen[0][0] == "view"


def test_plugins_json_and_sniffer_rest(server):
    http, key, sniffer = server
    status, body = _get(http.port, "/plugins.json")
    assert status == 200
    assert set(body["plugins"]) == {"reject-buy", "recorder"}
    assert body["plugins"]["reject-buy"]["type"] == INPUT_BLOCKER
    status, body = _get(
        http.port, "/plugins/inputsniffer/recorder/counts/today"
    )
    assert status == 200
    assert body["path"] == "counts/today"


def test_plugin_rest_unknown_404(server):
    http, _, _ = server
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(http.port, "/plugins/inputsniffer/nope/x")
    assert ei.value.code == 404


# -- engine server output blockers ------------------------------------------


def test_output_blocker_folds():
    ctx = PluginContext([UppercasePlugin()], load_env=False)
    out = ctx.block_output({}, {"q": 1}, {"label": "cat"})
    assert out == {"label": "CAT"}
    ctx.close()


def test_output_blocker_order():
    class A(EngineServerPlugin):
        plugin_name = "a"
        plugin_type = OUTPUT_BLOCKER

        def process(self, info, q, p):
            return p + "a"

    class B(A):
        plugin_name = "b"

        def process(self, info, q, p):
            return p + "b"

    ctx = PluginContext([A(), B()], load_env=False)
    assert ctx.block_output({}, {}, "") == "ab"
    ctx.close()


# -- registry / env loading -------------------------------------------------


def test_load_plugin_spec_class_and_instance():
    # pytest may re-import this file under a different module name, so
    # compare by plugin identity fields rather than class objects.
    p = load_plugin_spec("tests.test_plugins:RejectBuyBlocker")
    assert p.plugin_name == "reject-buy"
    assert p.plugin_type == INPUT_BLOCKER
    p2 = load_plugin_spec("tests.test_plugins:SAMPLE_PLUGIN")
    assert p2.plugin_name == "recorder"


def test_plugins_from_env(monkeypatch):
    monkeypatch.setenv(
        "PIO_PLUGINS",
        "tests.test_plugins:RejectBuyBlocker, nonexistent.module:x",
    )
    plugins = plugins_from_env()
    # bad spec is logged and skipped, good one loads
    assert len(plugins) == 1
    assert plugins[0].plugin_name == "reject-buy"


def test_bad_spec_raises():
    with pytest.raises(ValueError):
        load_plugin_spec("no_colon_here")


def test_plugin_internal_keyerror_not_masked():
    """A KeyError raised inside a plugin's handle_rest must surface as a
    500 plugin error, not a 404 'plugin not found'."""

    class Broken(EventServerPlugin):
        plugin_name = "broken"
        plugin_type = INPUT_SNIFFER

        def handle_rest(self, path, query):
            return query["missing-param"]

    ctx = PluginContext([Broken()], load_env=False)
    try:
        with pytest.raises(KeyError):
            ctx.handle_rest("inputsniffer", "broken", "x", {})
        from predictionio_tpu.serving.plugins import PluginNotFound

        with pytest.raises(PluginNotFound):
            ctx.handle_rest("inputsniffer", "nope", "x", {})
    finally:
        ctx.close()
