"""Transactional model generations: checksum manifests, write-all-then-
commit publish, quarantine + last-good fallback, and localfs atomic
write discipline (docs/training.md "Model generations")."""

import glob
import os
import threading

import pytest

from fake_engine import FakeParams
from predictionio_tpu.core import persistence
from predictionio_tpu.core.persistence import (
    ModelIntegrityError,
    load_generation,
    load_manifest,
    manifest_id,
    publish_generation,
    quarantine_generation,
    sha256_hex,
)
from predictionio_tpu.core.workflow import load_deployment, run_train
from predictionio_tpu.data.storage import Model
from predictionio_tpu.data.storage.localfs import (
    LocalFSModels,
    atomic_write_bytes,
)
from predictionio_tpu.data.storage.memory import MemoryModels
from predictionio_tpu.obs import get_registry
from predictionio_tpu.parallel.mesh import ComputeContext


@pytest.fixture(scope="module")
def ctx():
    return ComputeContext.create(batch="gen-test")


class TestPublishLoad:
    def test_roundtrip_and_manifest(self):
        backend = MemoryModels()
        blob = b"model-bytes-1"
        manifest = publish_generation(
            backend, "g1", blob,
            watermark={"count": 42, "latestTime": "2026-08-03T00:00:00"},
            parent="g0",
        )
        assert load_generation(backend, "g1") == blob
        stored = load_manifest(backend, "g1")
        assert stored == manifest
        art = stored["artifacts"][0]
        assert art["sha256"] == sha256_hex(blob)
        assert art["bytes"] == len(blob)
        assert stored["parent"] == "g0"
        assert stored["watermark"]["count"] == 42

    def test_legacy_blob_without_manifest_loads(self):
        backend = MemoryModels()
        backend.insert(Model(id="old", models=b"legacy"))
        assert load_generation(backend, "old") == b"legacy"

    def test_corrupt_blob_raises_integrity_error(self):
        backend = MemoryModels()
        publish_generation(backend, "g1", b"good-bytes")
        backend.insert(Model(id="g1", models=b"good-bytez"))  # flipped
        with pytest.raises(ModelIntegrityError, match="sha256"):
            load_generation(backend, "g1")

    def test_truncated_blob_raises(self):
        backend = MemoryModels()
        publish_generation(backend, "g1", b"0123456789")
        backend.insert(Model(id="g1", models=b"01234"))
        with pytest.raises(ModelIntegrityError, match="torn write"):
            load_generation(backend, "g1")

    def test_manifest_without_blob_raises(self):
        """A crashed publish that somehow lost the artifact can never
        serve: the manifest's presence makes the loss an integrity
        failure, not a legacy load."""
        backend = MemoryModels()
        publish_generation(backend, "g1", b"bytes")
        backend.delete("g1")
        with pytest.raises(ModelIntegrityError, match="missing"):
            load_generation(backend, "g1")

    def test_unreadable_manifest_is_integrity_failure(self):
        backend = MemoryModels()
        publish_generation(backend, "g1", b"bytes")
        backend.insert(Model(id=manifest_id("g1"), models=b"{not json"))
        with pytest.raises(ModelIntegrityError, match="manifest"):
            load_generation(backend, "g1")

    def test_quarantine_emulation_moves_aside(self):
        backend = MemoryModels()
        publish_generation(backend, "g1", b"bytes")
        quarantine_generation(backend, "g1")
        assert backend.get("g1") is None
        assert backend.get(manifest_id("g1")) is None
        assert backend.get("quarantined/g1").models == b"bytes"


class TestLocalFS:
    def test_atomic_insert_no_tmp_left(self, tmp_path):
        backend = LocalFSModels({"PATH": str(tmp_path)})
        backend.insert(Model(id="m1", models=b"x" * 1000))
        assert backend.get("m1").models == b"x" * 1000
        assert not glob.glob(str(tmp_path / "*.tmp*"))

    def test_quarantine_renames_in_place(self, tmp_path):
        backend = LocalFSModels({"PATH": str(tmp_path)})
        backend.insert(Model(id="m1", models=b"payload"))
        assert backend.quarantine("m1") is True
        assert backend.get("m1") is None
        moved = glob.glob(str(tmp_path / "*.quarantined.*"))
        assert len(moved) == 1
        with open(moved[0], "rb") as f:
            assert f.read() == b"payload"  # bytes kept for forensics

    def test_quarantine_missing_returns_false(self, tmp_path):
        backend = LocalFSModels({"PATH": str(tmp_path)})
        assert backend.quarantine("nope") is False

    def test_atomic_write_replaces_and_cleans(self, tmp_path):
        target = str(tmp_path / "f.bin")
        atomic_write_bytes(target, b"a")
        atomic_write_bytes(target, b"b")
        with open(target, "rb") as f:
            assert f.read() == b"b"
        assert not glob.glob(str(tmp_path / "*.tmp*"))

    def test_concurrent_publishers_never_tear(self, tmp_path):
        """Two racing publishers of the SAME id: the final file is one
        writer's complete payload, never an interleaving — the
        satellite's torn-generation proof."""
        backend = LocalFSModels({"PATH": str(tmp_path)})
        payloads = [bytes([i]) * 65536 for i in range(4)]
        errors = []

        def publish(payload):
            try:
                for _ in range(8):
                    backend.insert(Model(id="shared", models=payload))
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=publish, args=(p,)) for p in payloads
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        final = backend.get("shared").models
        assert final in payloads  # complete payload, no interleave
        assert not glob.glob(str(tmp_path / "*.tmp*"))


def _fake_engine():
    from fake_engine import FakePreparator, FakeDataSource
    from predictionio_tpu.core import Engine
    from test_engine_server import DictQueryAlgorithm, DictServing

    return Engine(
        FakeDataSource, FakePreparator, DictQueryAlgorithm, DictServing
    )


def _fake_params():
    from predictionio_tpu.core import EngineParams

    return EngineParams(
        data_source=("", FakeParams(id=1)),
        preparator=("", FakeParams(id=2)),
        algorithms=[("", FakeParams(id=3))],
        serving=("", FakeParams()),
    )


class TestDeployFallback:
    def test_run_train_publishes_manifest(self, ctx, memory_storage):
        iid = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
            watermark={"count": 7, "latestTime": ""},
        )
        backend = memory_storage.get_model_data_models()
        manifest = load_manifest(backend, iid)
        assert manifest is not None
        assert manifest["watermark"]["count"] == 7
        assert manifest["parent"] is None
        # second train records the first as its parent generation
        iid2 = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        assert load_manifest(backend, iid2)["parent"] == iid

    def test_corrupt_latest_falls_back_to_last_good(
        self, ctx, memory_storage
    ):
        g1 = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        g2 = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        backend = memory_storage.get_model_data_models()
        backend.insert(Model(id=g2, models=b"bit-flipped-garbage"))
        before = get_registry().counter(
            "pio_model_quarantined_total"
        ).value
        instance, algorithms, models, serving = load_deployment(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        assert instance.id == g1  # last-good serves
        after = get_registry().counter(
            "pio_model_quarantined_total"
        ).value
        assert after == before + 1
        # the corrupt generation was moved aside, not left loadable
        assert backend.get(g2) is None

    def test_explicit_corrupt_instance_raises(self, ctx, memory_storage):
        g1 = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        backend = memory_storage.get_model_data_models()
        backend.insert(Model(id=g1, models=b"garbage"))
        with pytest.raises(ModelIntegrityError):
            load_deployment(
                _fake_engine(), _fake_params(), engine_id="gen",
                instance_id=g1, ctx=ctx, storage=memory_storage,
            )

    def test_all_corrupt_raises_with_context(self, ctx, memory_storage):
        g1 = run_train(
            _fake_engine(), _fake_params(), engine_id="gen",
            ctx=ctx, storage=memory_storage,
        )
        backend = memory_storage.get_model_data_models()
        backend.insert(Model(id=g1, models=b"garbage"))
        with pytest.raises(RuntimeError, match="no loadable model"):
            load_deployment(
                _fake_engine(), _fake_params(), engine_id="gen",
                ctx=ctx, storage=memory_storage,
            )


class TestVersionGuard:
    def test_manifest_version_recorded(self):
        backend = MemoryModels()
        manifest = publish_generation(backend, "g1", b"x")
        assert manifest["version"] == persistence.GENERATION_VERSION
