"""HTTP store backend — the second *external* backend family.

Plays the role the reference's elasticsearch + hdfs backends play
(metadata documents: ``data/src/main/scala/org/apache/predictionio/data/
storage/elasticsearch/ESApps.scala:1`` and the six sibling DAOs; model
blobs: ``.../hdfs/HDFSModels.scala:1``): a storage *service* reached
over the network, so the metadata and model repositories can live on a
different host than the trainer, event server, and engine servers —
the multi-host TPU topology's control plane.

The service side is :class:`predictionio_tpu.serving.store_server
.StoreServer` (``pio-tpu storeserver``), which persists through any
*local* backend (sqlite + localfs by default). This module is the
client: DAO implementations that speak the JSON/HTTP protocol, plus the
record↔JSON codecs shared with the server so the wire shape has a
single definition.

Config keys (``PIO_STORAGE_SOURCES_<NAME>_*``):

* ``URL``  — base URL, e.g. ``http://10.0.0.5:7072`` (required)
* ``KEY``  — access key when the server was started with one
* ``TIMEOUT`` — per-request socket timeout in seconds (default 10)
* ``CACERT`` — CA bundle (PEM path) to trust for ``https`` URLs — the
  self-signed-cert workflow the serving tier documents
* ``VERIFY`` — set to ``false`` to skip https certificate verification
  (dev only)
"""

from __future__ import annotations

import datetime as _dt
import http.client
import json
import ssl
import threading
import urllib.parse
from typing import Any

from predictionio_tpu.obs import tracing
from predictionio_tpu.obs.context import get_request_id
from predictionio_tpu.serving import admission, resilience
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeysBackend,
    App,
    AppsBackend,
    Channel,
    ChannelsBackend,
    EngineInstance,
    EngineInstancesBackend,
    EngineManifest,
    EngineManifestsBackend,
    EvaluationInstance,
    EvaluationInstancesBackend,
    EventsBackend,
    Model,
    ModelsBackend,
    PartialBatchError,
    StorageError,
)
from predictionio_tpu.data.storage.sql_common import from_iso, iso

#: write-sequencing header for event inserts: ``<writer-id>:<seq>``.
#: A torn send (connection died after the request left, before the
#: response arrived) is ambiguous for a POST — the server may or may
#: not have committed. A replay carrying the SAME sequence token lets
#: the server answer from its dedupe cache instead of inserting twice,
#: which matters for the append-only eventlog backend where a duplicate
#: id would otherwise land as a second record. Used by the replicated
#: store tier (docs/storage.md "Replication & failover").
STORE_SEQ_HEADER = "X-PIO-Store-Seq"
#: marks a hinted-handoff replay: the server must fall back to an
#: id-existence check even when it already knows the writer — by replay
#: time, anti-entropy may have pulled the same events from a sibling,
#: and the monotonic-seq shortcut alone would append them twice
STORE_REPLAY_HEADER = "X-PIO-Store-Replay"

#: wire encoding for the tri-state target-entity filters
#: (``Option[Option[String]]`` semantics, base.EventsBackend.find):
#: param absent = no filter (Ellipsis), this sentinel = "must be
#: absent" (None), anything else = "must match".
TRI_NULL = "__null__"

# --------------------------------------------------------------------------
# record ↔ JSON codecs (single wire-shape definition, used by both sides)
# --------------------------------------------------------------------------


def app_to_json(a: App) -> dict:
    return {"id": a.id, "name": a.name, "description": a.description}


def app_from_json(d: dict) -> App:
    return App(id=d["id"], name=d["name"], description=d.get("description"))


def access_key_to_json(k: AccessKey) -> dict:
    return {"key": k.key, "appid": k.appid, "events": list(k.events)}


def access_key_from_json(d: dict) -> AccessKey:
    return AccessKey(
        key=d["key"], appid=d["appid"], events=tuple(d.get("events", ()))
    )


def channel_to_json(c: Channel) -> dict:
    return {"id": c.id, "name": c.name, "appid": c.appid}


def channel_from_json(d: dict) -> Channel:
    return Channel(id=d["id"], name=d["name"], appid=d["appid"])


def manifest_to_json(m: EngineManifest) -> dict:
    return {
        "id": m.id,
        "version": m.version,
        "name": m.name,
        "description": m.description,
        "files": list(m.files),
        "engine_factory": m.engine_factory,
    }


def manifest_from_json(d: dict) -> EngineManifest:
    return EngineManifest(
        id=d["id"],
        version=d["version"],
        name=d["name"],
        description=d.get("description"),
        files=tuple(d.get("files", ())),
        engine_factory=d.get("engine_factory", ""),
    )


def engine_instance_to_json(e: EngineInstance) -> dict:
    return {
        "id": e.id,
        "status": e.status,
        "start_time": iso(e.start_time),
        "end_time": iso(e.end_time),
        "engine_id": e.engine_id,
        "engine_version": e.engine_version,
        "engine_variant": e.engine_variant,
        "engine_factory": e.engine_factory,
        "batch": e.batch,
        "env": dict(e.env),
        "mesh_conf": dict(e.mesh_conf),
        "data_source_params": e.data_source_params,
        "preparator_params": e.preparator_params,
        "algorithms_params": e.algorithms_params,
        "serving_params": e.serving_params,
    }


def engine_instance_from_json(d: dict) -> EngineInstance:
    return EngineInstance(
        id=d["id"],
        status=d["status"],
        start_time=from_iso(d["start_time"]),
        end_time=from_iso(d["end_time"]),
        engine_id=d["engine_id"],
        engine_version=d["engine_version"],
        engine_variant=d["engine_variant"],
        engine_factory=d["engine_factory"],
        batch=d.get("batch", ""),
        env=dict(d.get("env", {})),
        mesh_conf=dict(d.get("mesh_conf", {})),
        data_source_params=d.get("data_source_params", "{}"),
        preparator_params=d.get("preparator_params", "{}"),
        algorithms_params=d.get("algorithms_params", "[]"),
        serving_params=d.get("serving_params", "{}"),
    )


def evaluation_instance_to_json(e: EvaluationInstance) -> dict:
    return {
        "id": e.id,
        "status": e.status,
        "start_time": iso(e.start_time),
        "end_time": iso(e.end_time),
        "evaluation_class": e.evaluation_class,
        "engine_params_generator_class": e.engine_params_generator_class,
        "batch": e.batch,
        "env": dict(e.env),
        "evaluator_results": e.evaluator_results,
        "evaluator_results_html": e.evaluator_results_html,
        "evaluator_results_json": e.evaluator_results_json,
    }


def evaluation_instance_from_json(d: dict) -> EvaluationInstance:
    return EvaluationInstance(
        id=d["id"],
        status=d["status"],
        start_time=from_iso(d["start_time"]),
        end_time=from_iso(d["end_time"]),
        evaluation_class=d.get("evaluation_class", ""),
        engine_params_generator_class=d.get(
            "engine_params_generator_class", ""
        ),
        batch=d.get("batch", ""),
        env=dict(d.get("env", {})),
        evaluator_results=d.get("evaluator_results", ""),
        evaluator_results_html=d.get("evaluator_results_html", ""),
        evaluator_results_json=d.get("evaluator_results_json", ""),
    )


def _q(raw) -> str:
    """Percent-encode one path segment (ids may contain '/', '%', …);
    the server unquotes symmetrically."""
    return urllib.parse.quote(str(raw), safe="")


# --------------------------------------------------------------------------
# HTTP client
# --------------------------------------------------------------------------


class StoreCircuitOpen(StorageError, resilience.CircuitOpenError):
    """The store target's breaker is open: fail fast, don't connect.

    Doubly typed on purpose: DAO callers keep their ``StorageError``
    contract, while the HTTP layer's
    :class:`~predictionio_tpu.serving.resilience.CircuitOpenError`
    mapping turns it into a retryable 503 instead of a 500."""

    def __init__(self, target: str):
        StorageError.__init__(
            self,
            f"store server {target} circuit open; "
            "fast-failing without a request",
        )
        self.target = target


class HTTPStoreClient:
    """Keep-alive JSON/HTTP client for one store server.

    One pooled connection per thread (serving and training code hit the
    DAOs from multiple threads); a request on a connection the server
    has since closed is retried once on a fresh socket.

    Resilience (docs/robustness.md): hops forward the caller's
    remaining ``X-PIO-Deadline`` budget (and cap their socket timeout
    by it); idempotent operations (GET/HEAD/PUT/DELETE — every DAO
    write here is a keyed upsert) retry transport errors and 5xx
    responses with jittered exponential backoff inside that budget; and
    the target sits behind a process-wide circuit breaker that
    fast-fails with :class:`StoreCircuitOpen` while the store is known
    to be down.
    """

    def __init__(self, config: dict):
        url = config.get("URL")
        if not url:
            raise StorageError(
                "httpstore source needs PIO_STORAGE_SOURCES_<NAME>_URL "
                "(e.g. http://host:7072)"
            )
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme not in ("http", "https") or not parsed.hostname:
            raise StorageError(f"httpstore URL not understood: {url!r}")
        self._scheme = parsed.scheme
        self._host = parsed.hostname
        self._port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self._key = config.get("KEY")
        try:
            self._timeout = float(config.get("TIMEOUT", 10))
        except ValueError as e:
            raise StorageError(
                f"httpstore TIMEOUT not a number: {config.get('TIMEOUT')!r}"
            ) from e
        self._ssl_context = None
        if self._scheme == "https":
            cacert = config.get("CACERT")
            try:
                self._ssl_context = ssl.create_default_context(
                    cafile=cacert or None
                )
            except (OSError, ssl.SSLError) as e:
                raise StorageError(
                    f"httpstore CACERT {cacert!r} unusable: {e}"
                ) from e
            if str(config.get("VERIFY", "true")).lower() in (
                "false", "0", "no",
            ):
                self._ssl_context.check_hostname = False
                self._ssl_context.verify_mode = ssl.CERT_NONE
        self._local = threading.local()
        self._target = f"{self._host}:{self._port}"
        self._retry = resilience.RetryPolicy.from_env()
        self._breaker = resilience.get_breaker(self._target)

    def _connection(self) -> tuple[http.client.HTTPConnection, bool]:
        """Returns (connection, reused) — ``reused`` means the socket
        carried an earlier request and may have been idled-out by the
        server since."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            return conn, True
        if self._scheme == "https":
            conn = http.client.HTTPSConnection(
                self._host,
                self._port,
                timeout=self._timeout,
                context=self._ssl_context,
            )
        else:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout
            )
        self._local.conn = conn
        return conn, False

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def request(
        self,
        method: str,
        path: str,
        *,
        params: dict[str, Any] | None = None,
        json_body: Any = None,
        raw_body: bytes | None = None,
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes]:
        """One HTTP round trip; returns (status, body bytes)."""
        route = path  # pre-query-string, for bounded span cardinality
        if params:
            qs = urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
            if qs:
                path = f"{path}?{qs}"
        headers = dict(extra_headers or {})
        if self._key:
            headers["Authorization"] = f"Bearer {self._key}"
        # the caller's request ID rides every store hop (even with
        # tracing off) so event-server → store-server logs correlate;
        # with a span open, the hop also joins the distributed trace
        rid = get_request_id()
        if rid:
            headers["X-Request-ID"] = rid
        criticality = admission.get_criticality()
        if criticality != admission.DEFAULT:
            # propagated like the deadline: the store hop sheds by the
            # ORIGINATING request's class under overload
            headers[admission.CRITICALITY_HEADER] = criticality
        if json_body is not None:
            body = json.dumps(json_body).encode("utf-8")
            headers["Content-Type"] = "application/json"
        elif raw_body is not None:
            body = raw_body
            headers["Content-Type"] = "application/octet-stream"
        else:
            body = None
        with tracing.span(
            f"httpstore {method} {route}", host=self._host
        ) as span:
            if span is not None:
                headers[tracing.PARENT_SPAN_HEADER] = span.span_id
            return self._roundtrip(method, path, body, headers, span)

    def _roundtrip(
        self, method, path, body, headers, span
    ) -> tuple[int, bytes]:
        idempotent = method in resilience.IDEMPOTENT_METHODS
        deadline = resilience.get_deadline()
        attempt = 0  # budgeted (backed-off) retries consumed
        stale_replayed = False
        while True:
            if deadline is not None and deadline.expired:
                raise resilience.DeadlineExceeded(
                    f"deadline expired before store hop {method} {path}"
                )
            breaker_state = self._breaker.state
            if span is not None and breaker_state != resilience.CLOSED:
                span.set("breaker", breaker_state)
            if not self._breaker.allow():
                raise StoreCircuitOpen(self._target)
            if deadline is not None:
                # the hop forwards what is LEFT of the budget — retries
                # carry smaller budgets, and the server's admission
                # check can reject work we would discard anyway
                headers[resilience.DEADLINE_HEADER] = deadline.to_header()
            conn, reused = self._connection()
            # cap the socket wait by the remaining budget (and restore
            # the configured timeout on budget-less requests — the
            # pooled connection outlives any one deadline)
            capped = (
                self._timeout
                if deadline is None
                else deadline.cap(self._timeout)
            )
            conn.timeout = capped
            sent = False
            try:
                # a dead pooled socket raises EBADF right here — inside
                # the try, so it takes the same stale-replay path as a
                # send-phase failure
                if conn.sock is not None:
                    conn.sock.settimeout(capped)
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                self._drop_connection()
                # Stale keep-alive replay (free — the server cannot
                # have acted): a send-phase failure on a reused socket
                # (the request never arrived whole, any method), or a
                # response-phase disconnect/reset/garbage on a reused
                # socket for an *idempotent* method — the classic
                # first-request-after-server-restart race. After a
                # completed send, a bare disconnect is ambiguous for a
                # POST (the server may have committed the insert before
                # dying, and a replay would duplicate the row), so
                # non-idempotent methods surface the error instead.
                stale = reused and (
                    not sent
                    or (
                        idempotent
                        and isinstance(e, (
                            http.client.RemoteDisconnected,
                            http.client.BadStatusLine,
                            ConnectionResetError,
                        ))
                    )
                )
                if stale and not stale_replayed:
                    # no evidence about the target (the request never
                    # arrived whole) — release any half-open probe slot
                    # instead of leaving the breaker wedged half-open
                    self._breaker.release()
                    stale_replayed = True
                    continue
                if deadline is not None and deadline.expired:
                    # budget-starved timeout: OUR clock ran out, which
                    # says nothing about the target's health
                    self._breaker.release()
                    raise resilience.DeadlineExceeded(
                        f"deadline expired during store hop "
                        f"{method} {path}"
                    ) from e
                self._breaker.record_failure()
                # retry only while the breaker stayed closed: when THIS
                # failure tripped it, a backoff sleep followed by
                # "circuit open" would waste the wait and mask the
                # actual transport error
                if (
                    idempotent
                    and self._breaker.state == resilience.CLOSED
                    and self._retry.sleep_before_retry(attempt, deadline)
                ):
                    attempt += 1
                    continue
                raise StorageError(
                    f"store server {self._host}:{self._port} unreachable: "
                    f"{e}"
                ) from e
            if span is not None:
                span.set("status", resp.status)
                if attempt or stale_replayed:
                    span.set(
                        "retries", attempt + (1 if stale_replayed else 0)
                    )
            if resp.status >= 500:
                if resp.status == 504:
                    # the server ANSWERED — refusing our (expired)
                    # budget is the caller's fault, not the target's,
                    # and retrying an exhausted budget is pointless
                    self._breaker.record_success()
                    raise StorageError(
                        f"store server refused expired deadline "
                        f"(HTTP 504): "
                        f"{data[:200].decode('utf-8', 'replace')}"
                    )
                self._breaker.record_failure()
                if (
                    idempotent
                    and self._breaker.state == resilience.CLOSED
                    and self._retry.sleep_before_retry(attempt, deadline)
                ):
                    attempt += 1
                    continue
                raise StorageError(
                    f"store server error HTTP {resp.status}: "
                    f"{data[:200].decode('utf-8', 'replace')}"
                )
            self._breaker.record_success()
            if resp.status in (401, 403):
                raise StorageError(
                    "store server rejected the access key "
                    f"(HTTP {resp.status})"
                )
            return resp.status, data

    def json(
        self,
        method: str,
        path: str,
        *,
        params: dict[str, Any] | None = None,
        json_body: Any = None,
        not_found_ok: bool = False,
    ) -> Any:
        status, data = self.request(
            method, path, params=params, json_body=json_body
        )
        if status == 404 and not_found_ok:
            return None
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: {method} {path} -> HTTP {status}: "
                f"{data[:200].decode('utf-8', 'replace')}"
            )
        return json.loads(data) if data else None

    def close(self) -> None:
        self._drop_connection()


# --------------------------------------------------------------------------
# DAO implementations
# --------------------------------------------------------------------------


class HTTPApps(AppsBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, app: App) -> int | None:
        out = self._c.json("POST", "/meta/apps", json_body=app_to_json(app))
        return out.get("id")

    def get(self, app_id: int) -> App | None:
        d = self._c.json("GET", f"/meta/apps/{_q(app_id)}", not_found_ok=True)
        return app_from_json(d) if d else None

    def get_by_name(self, name: str) -> App | None:
        if not name:
            # a blank-valued query param would be dropped server-side
            # (parse_qs), turning this into get_all; no app can have an
            # empty name, so answer locally like every other backend
            return None
        out = self._c.json("GET", "/meta/apps", params={"name": name})
        return app_from_json(out[0]) if out else None

    def get_all(self) -> list[App]:
        return [app_from_json(d) for d in self._c.json("GET", "/meta/apps")]

    def update(self, app: App) -> bool:
        out = self._c.json(
            "PUT", f"/meta/apps/{_q(app.id)}", json_body=app_to_json(app)
        )
        return bool(out.get("ok"))

    def delete(self, app_id: int) -> bool:
        out = self._c.json("DELETE", f"/meta/apps/{_q(app_id)}")
        return bool(out.get("ok"))


class HTTPAccessKeys(AccessKeysBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, access_key: AccessKey) -> str | None:
        out = self._c.json(
            "POST",
            "/meta/access_keys",
            json_body=access_key_to_json(access_key),
        )
        return out.get("id")

    def get(self, key: str) -> AccessKey | None:
        d = self._c.json(
            "GET", f"/meta/access_keys/{_q(key)}", not_found_ok=True
        )
        return access_key_from_json(d) if d else None

    def get_all(self) -> list[AccessKey]:
        return [
            access_key_from_json(d)
            for d in self._c.json("GET", "/meta/access_keys")
        ]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            access_key_from_json(d)
            for d in self._c.json(
                "GET", "/meta/access_keys", params={"app_id": app_id}
            )
        ]

    def update(self, access_key: AccessKey) -> bool:
        out = self._c.json(
            "PUT",
            f"/meta/access_keys/{_q(access_key.key)}",
            json_body=access_key_to_json(access_key),
        )
        return bool(out.get("ok"))

    def delete(self, key: str) -> bool:
        out = self._c.json("DELETE", f"/meta/access_keys/{_q(key)}")
        return bool(out.get("ok"))


class HTTPChannels(ChannelsBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, channel: Channel) -> int | None:
        out = self._c.json(
            "POST", "/meta/channels", json_body=channel_to_json(channel)
        )
        return out.get("id")

    def get(self, channel_id: int) -> Channel | None:
        d = self._c.json(
            "GET", f"/meta/channels/{_q(channel_id)}", not_found_ok=True
        )
        return channel_from_json(d) if d else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            channel_from_json(d)
            for d in self._c.json(
                "GET", "/meta/channels", params={"app_id": app_id}
            )
        ]

    def delete(self, channel_id: int) -> bool:
        out = self._c.json("DELETE", f"/meta/channels/{_q(channel_id)}")
        return bool(out.get("ok"))


class HTTPEngineManifests(EngineManifestsBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, manifest: EngineManifest) -> None:
        self._c.json(
            "POST",
            "/meta/engine_manifests",
            json_body=manifest_to_json(manifest),
        )

    def get(self, manifest_id: str, version: str) -> EngineManifest | None:
        d = self._c.json(
            "GET",
            f"/meta/engine_manifests/{_q(manifest_id)}/{_q(version)}",
            not_found_ok=True,
        )
        return manifest_from_json(d) if d else None

    def get_all(self) -> list[EngineManifest]:
        return [
            manifest_from_json(d)
            for d in self._c.json("GET", "/meta/engine_manifests")
        ]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        status, data = self._c.request(
            "PUT",
            f"/meta/engine_manifests/{_q(manifest.id)}/{_q(manifest.version)}",
            params={"upsert": int(upsert)},
            json_body=manifest_to_json(manifest),
        )
        if status == 404:
            # the server maps the backend's KeyError (non-upsert update
            # of a missing manifest) to 404; restore the contract
            raise KeyError(
                f"engine manifest ({manifest.id}, {manifest.version}) "
                "not found"
            )
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: manifest update -> HTTP {status}: "
                f"{data[:200].decode('utf-8', 'replace')}"
            )

    def delete(self, manifest_id: str, version: str) -> bool:
        out = self._c.json(
            "DELETE", f"/meta/engine_manifests/{_q(manifest_id)}/{_q(version)}"
        )
        return bool(out.get("ok"))


class HTTPEngineInstances(EngineInstancesBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, instance: EngineInstance) -> str:
        out = self._c.json(
            "POST",
            "/meta/engine_instances",
            json_body=engine_instance_to_json(instance),
        )
        return out["id"]

    def get(self, instance_id: str) -> EngineInstance | None:
        d = self._c.json(
            "GET", f"/meta/engine_instances/{_q(instance_id)}", not_found_ok=True
        )
        return engine_instance_from_json(d) if d else None

    def get_all(self) -> list[EngineInstance]:
        return [
            engine_instance_from_json(d)
            for d in self._c.json("GET", "/meta/engine_instances")
        ]

    def _completed(
        self,
        engine_id: str,
        engine_version: str,
        engine_variant: str,
        latest: bool,
    ):
        return self._c.json(
            "GET",
            "/meta/engine_instances",
            params={
                "engine_id": engine_id,
                "engine_version": engine_version,
                "engine_variant": engine_variant,
                "completed": 1,
                "latest": int(latest),
            },
        )

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        out = self._completed(
            engine_id, engine_version, engine_variant, latest=True
        )
        return engine_instance_from_json(out[0]) if out else None

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        return [
            engine_instance_from_json(d)
            for d in self._completed(
                engine_id, engine_version, engine_variant, latest=False
            )
        ]

    def update(self, instance: EngineInstance) -> bool:
        out = self._c.json(
            "PUT",
            f"/meta/engine_instances/{_q(instance.id)}",
            json_body=engine_instance_to_json(instance),
        )
        return bool(out.get("ok"))

    def delete(self, instance_id: str) -> bool:
        out = self._c.json(
            "DELETE", f"/meta/engine_instances/{_q(instance_id)}"
        )
        return bool(out.get("ok"))


class HTTPEvaluationInstances(EvaluationInstancesBackend):
    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, instance: EvaluationInstance) -> str:
        out = self._c.json(
            "POST",
            "/meta/evaluation_instances",
            json_body=evaluation_instance_to_json(instance),
        )
        return out["id"]

    def get(self, instance_id: str) -> EvaluationInstance | None:
        d = self._c.json(
            "GET",
            f"/meta/evaluation_instances/{_q(instance_id)}",
            not_found_ok=True,
        )
        return evaluation_instance_from_json(d) if d else None

    def get_all(self) -> list[EvaluationInstance]:
        return [
            evaluation_instance_from_json(d)
            for d in self._c.json("GET", "/meta/evaluation_instances")
        ]

    def get_completed(self) -> list[EvaluationInstance]:
        return [
            evaluation_instance_from_json(d)
            for d in self._c.json(
                "GET", "/meta/evaluation_instances", params={"completed": 1}
            )
        ]

    def update(self, instance: EvaluationInstance) -> bool:
        out = self._c.json(
            "PUT",
            f"/meta/evaluation_instances/{_q(instance.id)}",
            json_body=evaluation_instance_to_json(instance),
        )
        return bool(out.get("ok"))

    def delete(self, instance_id: str) -> bool:
        out = self._c.json(
            "DELETE", f"/meta/evaluation_instances/{_q(instance_id)}"
        )
        return bool(out.get("ok"))


class HTTPModels(ModelsBackend):
    """Model blob store over HTTP (reference HDFSModels.scala:30-64:
    one opaque file per model id)."""

    def __init__(self, client: HTTPStoreClient):
        self._c = client

    def insert(self, model: Model) -> None:
        # end-to-end upload integrity: the server recomputes the digest
        # over the bytes it RECEIVED and refuses a mismatch with 422, so
        # a bit flipped in transit (or a truncation a proxy papered
        # over) never lands in the store. Read-side integrity is the
        # generation manifest's job (core/persistence.load_generation).
        import hashlib

        status, data = self._c.request(
            "PUT",
            f"/models/{_q(model.id)}",
            raw_body=model.models,
            extra_headers={
                "X-PIO-SHA256": hashlib.sha256(model.models).hexdigest()
            },
        )
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: model put -> HTTP {status}: "
                f"{data[:200].decode('utf-8', 'replace')}"
            )

    def get(self, model_id: str) -> Model | None:
        status, data = self._c.request(
            "GET", f"/models/{_q(model_id)}"
        )
        if status == 404:
            return None
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: model get -> HTTP {status}"
            )
        return Model(id=model_id, models=data)

    def delete(self, model_id: str) -> bool:
        out = self._c.json(
            "DELETE", f"/models/{_q(model_id)}"
        )
        return bool(out.get("ok"))

    def list_ids(self) -> list[str] | None:
        out = self._c.json("GET", "/models")
        ids = (out or {}).get("ids")
        return list(ids) if ids is not None else None


class HTTPEvents(EventsBackend):
    """Event DAO over the store server's ``/events`` routes.

    Completes the httpstore backend family for the replicated tier:
    a ``ReplicatedStore`` peer IS a store server, so event replication
    needs the event DAO to speak the same wire as metadata and models.
    Events are stamped with their UUID *client-side* before the POST —
    the server upserts by id on sqlite/memory and dedupes replays by
    ``X-PIO-Store-Seq`` on the append-only eventlog, so a retried send
    can never double-insert.
    """

    def __init__(self, client: HTTPStoreClient):
        self._c = client

    @staticmethod
    def _chan(channel_id: int | None) -> dict:
        return {} if channel_id is None else {"channel_id": channel_id}

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        out = self._c.json(
            "PUT", f"/events/{_q(app_id)}", params=self._chan(channel_id)
        )
        return bool(out.get("ok"))

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        out = self._c.json(
            "DELETE", f"/events/{_q(app_id)}", params=self._chan(channel_id)
        )
        return bool(out.get("ok"))

    def close(self) -> None:
        self._c.close()

    def _post(
        self,
        path: str,
        params: dict,
        json_body,
        store_seq: str | None,
        replay: bool = False,
    ) -> tuple[int, bytes]:
        headers = {}
        if store_seq:
            headers[STORE_SEQ_HEADER] = store_seq
        if replay:
            headers[STORE_REPLAY_HEADER] = "1"
        return self._c.request(
            "POST",
            path,
            params=params,
            json_body=json_body,
            extra_headers=headers or None,
        )

    def insert(
        self,
        event: Event,
        app_id: int,
        channel_id: int | None = None,
        *,
        store_seq: str | None = None,
        replay: bool = False,
    ) -> str:
        stamped = event.with_id(event.event_id)
        status, data = self._post(
            f"/events/{_q(app_id)}",
            self._chan(channel_id),
            stamped.to_json_dict(),
            store_seq,
            replay,
        )
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: event insert -> HTTP {status}: "
                f"{data[:200].decode('utf-8', 'replace')}"
            )
        out = json.loads(data) if data else {}
        return out.get("id") or stamped.event_id

    def insert_batch(
        self,
        events,
        app_id: int,
        channel_id: int | None = None,
        *,
        store_seq: str | None = None,
        replay: bool = False,
    ) -> list[str]:
        if not events:
            return []
        stamped = [e.with_id(e.event_id) for e in events]
        status, data = self._post(
            f"/events/{_q(app_id)}/batch",
            self._chan(channel_id),
            [e.to_json_dict() for e in stamped],
            store_seq,
            replay,
        )
        out = json.loads(data) if data else {}
        if status == 409 and "insertedIds" in out:
            # the server's durable-prefix report (a PartialBatchError on
            # its backend) rides a 409 — 5xx would be swallowed by the
            # transport layer before the body could be parsed
            raise PartialBatchError(
                out.get("error", "partial batch insert"),
                list(out["insertedIds"]),
            )
        if not 200 <= status < 300:
            raise StorageError(
                f"store server: event batch insert -> HTTP {status}: "
                f"{data[:200].decode('utf-8', 'replace')}"
            )
        return list(out.get("ids") or [e.event_id for e in stamped])

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        d = self._c.json(
            "GET",
            f"/events/{_q(app_id)}/one/{_q(event_id)}",
            params=self._chan(channel_id),
            not_found_ok=True,
        )
        return Event.from_json_dict(d) if d else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        out = self._c.json(
            "DELETE",
            f"/events/{_q(app_id)}/one/{_q(event_id)}",
            params=self._chan(channel_id),
        )
        return bool(out.get("ok"))

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names=None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ):
        params: dict[str, Any] = dict(self._chan(channel_id))
        if start_time is not None:
            params["start_time"] = start_time.isoformat()
        if until_time is not None:
            params["until_time"] = until_time.isoformat()
        if entity_type is not None:
            params["entity_type"] = entity_type
        if entity_id is not None:
            params["entity_id"] = entity_id
        if event_names is not None:
            # JSON-encoded so names containing separators round-trip
            params["event_names"] = json.dumps(list(event_names))
        if target_entity_type is not ...:
            params["target_entity_type"] = (
                TRI_NULL if target_entity_type is None
                else target_entity_type
            )
        if target_entity_id is not ...:
            params["target_entity_id"] = (
                TRI_NULL if target_entity_id is None else target_entity_id
            )
        if limit is not None:
            params["limit"] = limit
        if reversed:
            params["reversed"] = 1
        out = self._c.json(
            "GET", f"/events/{_q(app_id)}", params=params
        )
        for d in out or []:
            yield Event.from_json_dict(d)

    def watermark(
        self, app_id: int, channel_id: int | None = None
    ) -> dict:
        """The server's event-set summary for one (app, channel) —
        ``{"count", "checksum", "latest"}``. Anti-entropy compares the
        order-independent checksum between peers; a mismatch triggers a
        full pull (docs/storage.md "Replication & failover")."""
        return self._c.json(
            "GET",
            f"/events/{_q(app_id)}/watermark",
            params=self._chan(channel_id),
        )
