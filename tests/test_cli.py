"""CLI console tests (reference console/Console.scala command tree),
plus dashboard and admin server REST."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.cli.main import main
from predictionio_tpu.data import DataMap, Event


@pytest.fixture()
def cli(memory_storage, capsys):
    """Run the CLI against the process-default (memory) storage."""

    def run(*argv):
        code = main(list(argv))
        out = capsys.readouterr()
        return code, out.out, out.err

    return run


class TestAppCommands:
    def test_app_lifecycle(self, cli, memory_storage):
        code, out, _ = cli("app", "new", "myapp", "--description", "d")
        assert code == 0 and "Access Key:" in out
        code, out, _ = cli("app", "list")
        assert "myapp" in out
        code, out, _ = cli("app", "show", "myapp")
        info = json.loads(out)
        assert info["name"] == "myapp" and len(info["accessKeys"]) == 1
        # duplicate rejected
        code, _, err = cli("app", "new", "myapp")
        assert code == 1 and "already exists" in err
        code, out, _ = cli("app", "delete", "myapp")
        assert code == 0
        code, out, _ = cli("app", "list")
        assert "myapp" not in out

    def test_channels(self, cli, memory_storage):
        cli("app", "new", "chapp")
        code, out, _ = cli("app", "channel-new", "chapp", "ch1")
        assert code == 0
        code, out, _ = cli("app", "show", "chapp")
        assert json.loads(out)["channels"][0]["name"] == "ch1"
        code, _, err = cli("app", "channel-new", "chapp", "bad name!")
        assert code == 1
        code, out, _ = cli("app", "channel-delete", "chapp", "ch1")
        assert code == 0

    def test_accesskey(self, cli, memory_storage):
        cli("app", "new", "akapp")
        code, out, _ = cli(
            "accesskey", "new", "akapp", "--events", "view,buy"
        )
        assert code == 0
        key = out.strip().split(": ")[1]
        code, out, _ = cli("accesskey", "list", "akapp")
        assert key in out and "view,buy" in out
        code, out, _ = cli("accesskey", "delete", key)
        assert code == 0

    def test_data_delete(self, cli, memory_storage):
        cli("app", "new", "ddapp")
        app = memory_storage.get_meta_data_apps().get_by_name("ddapp")
        memory_storage.get_events().insert(
            Event(event="view", entity_type="u", entity_id="1"), app.id
        )
        code, _, _ = cli("app", "data-delete", "ddapp")
        assert code == 0
        assert list(memory_storage.get_events().find(app.id)) == []


class TestStatusVersionTemplates:
    def test_version(self, cli):
        code, out, _ = cli("version")
        assert code == 0 and out.strip()

    def test_status_ok(self, cli, memory_storage):
        code, out, _ = cli("status")
        assert code == 0
        assert "all ready to go" in out

    def test_template_list(self, cli):
        code, out, _ = cli("template", "list")
        assert code == 0
        for name in (
            "classification",
            "recommendation",
            "similarproduct",
            "ecommerce",
        ):
            assert name in out


class TestBuildTrainExportImport:
    def _seed(self, cli, storage):
        cli("app", "new", "clfapp")
        app = storage.get_meta_data_apps().get_by_name("clfapp")
        events = storage.get_events()
        rng = np.random.default_rng(0)
        for i in range(30):
            label = i % 2
            base = [8.0, 1.0, 1.0] if label == 0 else [1.0, 1.0, 8.0]
            f = np.clip(np.asarray(base) + rng.poisson(1.0, 3), 0, None)
            events.insert(
                Event(
                    event="$set",
                    entity_type="user",
                    entity_id=f"u{i}",
                    properties=DataMap(
                        {
                            "attr0": float(f[0]),
                            "attr1": float(f[1]),
                            "attr2": float(f[2]),
                            "plan": str(label),
                        }
                    ),
                ),
                app.id,
            )

    def test_build_validates_variant(self, cli, tmp_path):
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "id": "clf-test",
                    "engineFactory": "classification",
                    "datasource": {"params": {"app_name": "clfapp"}},
                    "algorithms": [
                        {"name": "naive", "params": {"lambda_": 0.5}}
                    ],
                }
            )
        )
        code, out, _ = cli("build", "--variant", str(variant))
        assert code == 0 and "OK" in out

    def test_build_registers_manifest_and_unregister(
        self, cli, memory_storage, tmp_path
    ):
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "id": "clf-test",
                    "engineFactory": "classification",
                    "algorithms": [
                        {"name": "naive", "params": {"lambda_": 0.5}}
                    ],
                }
            )
        )
        code, out, _ = cli("build", "--variant", str(variant))
        assert code == 0 and "Registered engine clf-test" in out
        manifests = memory_storage.get_meta_data_engine_manifests()
        all_m = manifests.get_all()
        assert len(all_m) == 1
        m = all_m[0]
        assert m.id == "clf-test"
        assert m.engine_factory == "classification"
        code, out, _ = cli(
            "unregister", "--engine-id", "clf-test",
            "--engine-version", m.version,
        )
        assert code == 0 and manifests.get_all() == []
        code, _, err = cli(
            "unregister", "--engine-id", "clf-test",
            "--engine-version", m.version,
        )
        assert code == 1 and "not registered" in err

    def test_upgrade_migrates_events_between_sources(
        self, cli, tmp_path, monkeypatch
    ):
        from predictionio_tpu.data.storage import Storage, set_storage

        storage = Storage(
            env={
                "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
                "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "m.sqlite"),
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
            }
        )
        set_storage(storage)
        try:
            code, out, _ = cli("app", "new", "migapp")
            assert code == 0
            app = storage.get_meta_data_apps().get_by_name("migapp")
            events = storage.get_events()
            for i in range(7):
                events.insert(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{i}",
                        target_entity_type="item",
                        target_entity_id="i1",
                    ),
                    app.id,
                )
            code, out, _ = cli(
                "upgrade", "--from", "MEM", "--to", "SQL",
                "--app", "migapp",
            )
            assert code == 0 and "Migrated 7 events" in out
            migrated = list(storage.backend_for_source("SQL").find(app.id))
            assert len(migrated) == 7
            assert {e.entity_id for e in migrated} == {
                f"u{i}" for i in range(7)
            }
        finally:
            set_storage(None)

    def test_build_rejects_bad_params(self, cli, tmp_path):
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "engineFactory": "classification",
                    "algorithms": [
                        {"name": "naive", "params": {"lambdaaa": 0.5}}
                    ],
                }
            )
        )
        with pytest.raises(Exception, match="unknown params"):
            cli("build", "--variant", str(variant))

    def test_train_via_cli_and_variant(self, cli, memory_storage, tmp_path):
        self._seed(cli, memory_storage)
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "id": "clf-cli",
                    "engineFactory": "classification",
                    "datasource": {"params": {"app_name": "clfapp"}},
                }
            )
        )
        code, out, _ = cli("train", "--variant", str(variant))
        assert code == 0 and "Training completed" in out
        insts = memory_storage.get_meta_data_engine_instances().get_all()
        assert insts[0].engine_id == "clf-cli"
        assert insts[0].status == "COMPLETED"

    def test_export_import_roundtrip(self, cli, memory_storage, tmp_path):
        self._seed(cli, memory_storage)
        out_file = tmp_path / "events.jsonl"
        code, out, _ = cli(
            "export", "--appname", "clfapp", "--output", str(out_file)
        )
        assert code == 0 and "Exported 30" in out
        cli("app", "new", "copyapp")
        code, out, _ = cli(
            "import",
            "--appname",
            "copyapp",
            "--input",
            str(out_file),
        )
        assert code == 0 and "Imported 30" in out
        app = memory_storage.get_meta_data_apps().get_by_name("copyapp")
        assert len(list(memory_storage.get_events().find(app.id))) == 30

    def test_export_import_npz_roundtrip(
        self, cli, memory_storage, tmp_path
    ):
        """Columnar format (the reference's parquet analogue,
        EventsToFile.scala:40-104): full-fidelity export → import."""
        self._seed(cli, memory_storage)
        out_file = tmp_path / "events.npz"
        code, out, _ = cli(
            "export", "--appname", "clfapp", "--output", str(out_file)
        )
        assert code == 0 and "Exported 30" in out
        cli("app", "new", "npzapp")
        code, out, _ = cli(
            "import", "--appname", "npzapp", "--input", str(out_file)
        )
        assert code == 0 and "Imported 30" in out
        src = memory_storage.get_meta_data_apps().get_by_name("clfapp")
        dst = memory_storage.get_meta_data_apps().get_by_name("npzapp")
        events = memory_storage.get_events()
        orig = list(events.find(src.id))
        copy = list(events.find(dst.id))
        # exact fidelity: every field except the backend-assigned id
        strip = lambda e: (  # noqa: E731
            e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, e.properties.to_dict(), e.event_time,
            e.tags, e.pr_id, e.creation_time,
        )
        assert sorted(map(strip, copy)) == sorted(map(strip, orig))

    def test_eventfile_rejects_foreign_npz(self, tmp_path):
        import numpy as _np

        from predictionio_tpu.data.eventfile import read_events_npz

        bad = tmp_path / "other.npz"
        _np.savez(bad, x=_np.arange(3))
        with pytest.raises(ValueError, match="not an event export"):
            list(read_events_npz(str(bad)))


def _call(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            ct = resp.headers.get("Content-Type", "")
            raw = resp.read()
            return resp.status, (
                json.loads(raw) if "json" in ct else raw.decode()
            )
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class TestAdminServer:
    def test_app_rest(self, memory_storage):
        from predictionio_tpu.serving.admin import create_admin_server

        http = create_admin_server(
            host="127.0.0.1", port=0, storage=memory_storage
        )
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            assert _call(f"{base}/")[1] == {"status": "alive"}
            status, body = _call(
                f"{base}/cmd/app", "POST", {"name": "adminapp"}
            )
            assert status == 201 and body["accessKey"]
            status, body = _call(f"{base}/cmd/app")
            assert [a["name"] for a in body] == ["adminapp"]
            # duplicate → 409
            status, _ = _call(
                f"{base}/cmd/app", "POST", {"name": "adminapp"}
            )
            assert status == 409
            status, _ = _call(f"{base}/cmd/app/adminapp/data", "DELETE")
            assert status == 200
            status, _ = _call(f"{base}/cmd/app/adminapp", "DELETE")
            assert status == 200
            status, _ = _call(f"{base}/cmd/app/nope", "DELETE")
            assert status == 404
        finally:
            http.shutdown()


class TestDashboard:
    def test_lists_completed_evaluations(self, memory_storage):
        import datetime as dt

        from predictionio_tpu.data.storage import EvaluationInstance
        from predictionio_tpu.serving.dashboard import create_dashboard

        memory_storage.get_meta_data_evaluation_instances().insert(
            EvaluationInstance(
                id="eval1",
                status="EVALCOMPLETED",
                start_time=dt.datetime.now(dt.timezone.utc),
                end_time=dt.datetime.now(dt.timezone.utc),
                evaluation_class="MyEval",
                evaluator_results="[Metric] best: 0.9",
                evaluator_results_html="<table><tr><td>0.9</td></tr></table>",
                evaluator_results_json='{"bestScore": 0.9}',
            )
        )
        http = create_dashboard(
            host="127.0.0.1", port=0, storage=memory_storage
        )
        http.start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            status, body = _call(f"{base}/")
            assert status == 200
            assert "MyEval" in body and "eval1"[:8] in body
            status, body = _call(f"{base}/engine_instances/eval1")
            assert status == 200 and "0.9" in body
            status, _ = _call(f"{base}/engine_instances/nope")
            assert status == 404
        finally:
            http.shutdown()


class TestTemplateAndRun:
    def test_template_list(self, cli):
        code, out, _ = cli("template", "list")
        assert code == 0
        assert "classification" in out and "recommendation" in out

    def test_help_verb(self, cli):
        """Reference Console has an explicit `help` verb besides -h."""
        code, out, _ = cli("help")
        assert code == 0
        for verb in ("train", "deploy", "eventserver", "template"):
            assert verb in out

    def test_shell_verb_runs_piped_commands(self):
        """`pio-tpu shell` preloads storage/ctx/event_store; EOF on
        stdin exits cleanly (the bin/pio-shell analogue)."""
        import subprocess
        import sys

        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        out = subprocess.run(
            [sys.executable, "-m", "predictionio_tpu.cli.main", "shell"],
            input="print('CTX-AXES', sorted(ctx.mesh.axis_names))\n",
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert out.returncode == 0, out.stderr
        assert "CTX-AXES ['data', 'model']" in out.stdout
        assert "preloaded: storage" in out.stderr + out.stdout

    def test_template_get(self, cli, tmp_path):
        dst = str(tmp_path / "myengine")
        code, out, _ = cli(
            "template", "get", "classification", dst,
            "--engine-id", "my-classifier",
        )
        assert code == 0
        variant = json.loads((tmp_path / "myengine" / "engine.json").read_text())
        assert variant["id"] == "my-classifier"

    def test_template_get_missing(self, cli, tmp_path):
        code, _, err = cli(
            "template", "get", "no-such-template", str(tmp_path / "x")
        )
        assert code == 1 and "not found" in err

    def test_template_get_nonempty_dest(self, cli, tmp_path):
        (tmp_path / "occupied").mkdir()
        (tmp_path / "occupied" / "f").write_text("x")
        code, _, err = cli(
            "template", "get", "classification", str(tmp_path / "occupied")
        )
        assert code == 1 and "empty directory" in err

    @staticmethod
    def _make_git_repo(tmp_path, tag: str = "") -> str:
        """A local git repo playing the remote gallery (the reference
        fetches GitHub tag tarballs, Template.scala:226-369; offline
        here via file://)."""
        import subprocess

        repo = tmp_path / "gallery-repo"
        (repo / "engines" / "myrec").mkdir(parents=True)
        (repo / "engines" / "myrec" / "engine.json").write_text(
            json.dumps({"id": "default", "engineFactory": "x:y"})
        )
        (repo / "engines" / "myrec" / "engine.py").write_text("# engine\n")
        (repo / "README.md").write_text("gallery\n")

        def git(*argv):
            subprocess.run(
                ["git", "-C", str(repo), *argv],
                check=True, capture_output=True,
                env={
                    **os.environ,
                    "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                    "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
                },
            )

        git("init", "-q")
        git("add", "-A")
        git("commit", "-qm", "gallery")
        if tag:
            git("tag", tag)
        return f"file://{repo}"

    def test_template_get_from_git_url(self, cli, tmp_path):
        url = self._make_git_repo(tmp_path)
        dst = str(tmp_path / "fetched")
        code, out, _ = cli(
            "template", "get", url, dst,
            "--subdir", "engines/myrec", "--engine-id", "mine",
        )
        assert code == 0
        variant = json.loads(
            (tmp_path / "fetched" / "engine.json").read_text()
        )
        assert variant["id"] == "mine"
        assert (tmp_path / "fetched" / "engine.py").exists()
        # the clone's metadata must not leak into the project
        assert not (tmp_path / "fetched" / ".git").exists()

    def test_template_get_git_whole_repo_and_ref(self, cli, tmp_path):
        url = self._make_git_repo(tmp_path, tag="v1.0")
        dst = str(tmp_path / "whole")
        code, _out, _ = cli("template", "get", url, dst, "--ref", "v1.0")
        assert code == 0
        assert (tmp_path / "whole" / "README.md").exists()

    def test_template_get_git_bad_ref(self, cli, tmp_path):
        url = self._make_git_repo(tmp_path)
        code, _, err = cli(
            "template", "get", url, str(tmp_path / "x"),
            "--ref", "no-such-tag",
        )
        assert code == 1 and "cannot fetch" in err

    def test_template_get_git_bad_subdir(self, cli, tmp_path):
        url = self._make_git_repo(tmp_path)
        code, _, err = cli(
            "template", "get", url, str(tmp_path / "x"),
            "--subdir", "engines/nope",
        )
        assert code == 1 and "--subdir" in err

    @pytest.mark.parametrize("subdir", ["../..", "/etc", "engines/../.."])
    def test_template_get_subdir_confined_to_clone(
        self, cli, tmp_path, subdir
    ):
        """An absolute or ../-traversing --subdir must not scaffold
        from the host filesystem."""
        url = self._make_git_repo(tmp_path)
        code, _, err = cli(
            "template", "get", url, str(tmp_path / "x"),
            "--subdir", subdir,
        )
        assert code == 1 and "--subdir" in err
        assert not (tmp_path / "x").exists()

    def test_template_get_ref_rejected_for_local_source(
        self, cli, tmp_path
    ):
        code, _, err = cli(
            "template", "get", "classification", str(tmp_path / "x"),
            "--subdir", "sub",
        )
        assert code == 1 and "git sources" in err

    def test_template_get_symlinks_not_dereferenced(self, cli, tmp_path):
        """A hostile template repo must not exfiltrate host files via
        symlinks: links are preserved as links, never followed."""
        secret = tmp_path / "secret.txt"
        secret.write_text("host-private")
        url = self._make_git_repo(tmp_path)
        repo = tmp_path / "gallery-repo"
        os.symlink(str(secret), repo / "engines" / "myrec" / "leak")
        import subprocess

        subprocess.run(
            ["git", "-C", str(repo), "add", "-A"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["git", "-C", str(repo), "-c", "user.name=t",
             "-c", "user.email=t@t", "commit", "-qm", "link"],
            check=True, capture_output=True,
        )
        dst = tmp_path / "linked"
        code, _, _ = cli(
            "template", "get", url, str(dst), "--subdir", "engines/myrec",
        )
        assert code == 0
        # the scaffold carries the LINK itself, not a dereferenced copy
        # of whatever it pointed at on the fetching host
        assert os.path.islink(dst / "leak")

    def test_template_get_unreachable_url(self, cli, tmp_path):
        code, _, err = cli(
            "template", "get",
            f"file://{tmp_path}/definitely-missing.git",
            str(tmp_path / "x"),
        )
        assert code == 1 and "cannot fetch" in err

    def test_run(self, cli, tmp_path, monkeypatch):
        (tmp_path / "fakejob.py").write_text(
            "def job(ctx):\n"
            "    return {'devices': ctx.mesh.devices.size}\n"
        )
        monkeypatch.chdir(tmp_path)
        code, out, _ = cli("run", "fakejob:job")
        assert code == 0
        assert json.loads(out)["devices"] >= 1

    def test_run_bad_target(self, cli):
        code, _, err = cli("run", "nocolon")
        assert code == 1 and "module:function" in err


class TestDeployFlags:
    def test_max_batch_zero_rejected(self, cli):
        code, _out, err = cli(
            "deploy", "--variant", "nope.json", "--max-batch", "0"
        )
        assert code != 0 and "max-batch" in err

    def test_variant_mesh_conf_used_and_recorded(
        self, cli, memory_storage, tmp_path
    ):
        """engine.json meshConf (the reference's embedded sparkConf,
        WorkflowUtils.extractSparkConf:308-327) selects the mesh when
        no --mesh-shape flag is given; the topology lands on the
        EngineInstance record."""
        TestBuildTrainExportImport()._seed(cli, memory_storage)
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "id": "clf-mesh",
                    "engineFactory": "classification",
                    "datasource": {"params": {"app_name": "clfapp"}},
                    "meshConf": {"shape": "4,2", "batch": "from-variant"},
                }
            )
        )
        code, out, _ = cli("train", "--variant", str(variant))
        assert code == 0 and "Training completed" in out
        inst = memory_storage.get_meta_data_engine_instances().get_all()[-1]
        assert inst.mesh_conf["shape"] == "4,2"
        assert inst.mesh_conf["axes"] == "data,model"
        assert inst.mesh_conf["devices"] == "8"
        assert inst.batch == "from-variant"  # meshConf.batch recorded

    def test_bad_mesh_shape_is_clean_cli_error(self, cli, tmp_path):
        variant = tmp_path / "engine.json"
        variant.write_text(
            json.dumps(
                {
                    "id": "clf-bad",
                    "engineFactory": "classification",
                    "meshConf": {"shape": "data,model"},
                }
            )
        )
        with pytest.raises(SystemExit, match="mesh shape"):
            cli("train", "--variant", str(variant))

    def test_negative_max_wait_rejected(self, cli):
        code, _out, err = cli(
            "deploy", "--variant", "nope.json", "--max-wait-ms", "-5"
        )
        assert code != 0 and "max-wait-ms" in err

    def test_tenant_parser_flags(self):
        from predictionio_tpu.cli.main import build_parser

        args = build_parser().parse_args([
            "deploy", "--tenant", "alice=va", "--tenant", "bob=vb",
            "--pool-budget-bytes", "1048576", "--quantize", "int8",
        ])
        assert args.tenant == ["alice=va", "bob=vb"]
        assert args.pool_budget_bytes == 1048576
        assert args.quantize == "int8"

    def test_tenant_bad_spec_rejected(self, cli):
        code, _out, err = cli(
            "deploy", "--variant", "nope.json", "--tenant", "noequals"
        )
        assert code != 0 and "NAME=VARIANT" in err

    def test_tenant_canary_mutually_exclusive(self, cli):
        code, _out, err = cli(
            "deploy", "--variant", "nope.json",
            "--tenant", "alice=va", "--canary",
        )
        assert code != 0 and "mutually exclusive" in err


class TestFleetCLI:
    def test_router_parser_fleet_flags(self):
        from predictionio_tpu.cli.main import build_parser

        args = build_parser().parse_args([
            "router", "--state-file", "/tmp/fleet.json", "--fleet-gate",
            "--spawn-replica",
            "python tests/fleet_replica_child.py --port {port} "
            "--generation {generation}",
            "--min-replicas", "2", "--max-replicas", "5",
            "--state-max-age", "120",
        ])
        assert args.state_file == "/tmp/fleet.json"
        assert args.fleet_gate
        assert "{port}" in args.spawn_replica
        assert args.min_replicas == 2 and args.max_replicas == 5
        assert args.state_max_age == 120.0

    def test_trainer_parser_router_flags(self):
        from predictionio_tpu.cli.main import build_parser

        args = build_parser().parse_args([
            "trainer", "--app", "a",
            "--router-url", "http://router:8100",
            "--router-key", "k", "--promote-timeout", "42",
        ])
        assert args.router_url == "http://router:8100"
        assert args.router_key == "k"
        assert args.promote_timeout == 42.0

    def test_status_router_url_prints_fleet_summary(self, cli):
        from predictionio_tpu.obs import MetricRegistry
        from predictionio_tpu.serving.router import ServingRouter

        router = ServingRouter(
            probe_interval_s=999.0, registry=MetricRegistry()
        )
        router.add_replica(
            "http://127.0.0.1:9001", replica_id="a", generation="g1"
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            code, out, _ = cli(
                "status", "--router-url",
                f"http://127.0.0.1:{http.port}",
            )
            assert code == 0
            assert "fleet: replicas=1" in out
            assert "generation=g1" in out
            assert "swap=none" in out
            # the metrics scrape rides along (router gauges visible)
            assert "pio_router_replica_healthy" in out
        finally:
            router.close()
            http.shutdown()

    def test_status_router_url_rejects_non_router(self, cli):
        code, _out, err = cli(
            "status", "--router-url", "http://127.0.0.1:1"
        )
        assert code == 1 and "ERROR" in err


class TestObservabilityCLI:
    """ISSUE 16: the fleet-health status line and the profile verb."""

    def test_fleet_health_line_formats(self):
        from predictionio_tpu.cli.main import _fleet_health_line

        line = _fleet_health_line(
            {
                "goodputQps": 12.5,
                "burnRate": 0.8,
                "replicas": {
                    "b": {"stale": True, "residentBytes": 3 * 2**20},
                    "a": {
                        "stale": False,
                        "hbmUsedBytes": 600.0,
                        "hbmLimitBytes": 1000.0,
                        "hbmHeadroomBytes": 400.0,
                    },
                },
            }
        )
        assert line.startswith("health: goodput=12.5qps burn=0.8")
        assert "a[hbmFree=400B]" in line
        assert "b[rss=3.00MiB stale]" in line
        assert _fleet_health_line(None) is None

    def test_status_router_url_prints_health_and_federation(self, cli):
        from predictionio_tpu.obs import MetricRegistry
        from predictionio_tpu.serving.router import ServingRouter

        router = ServingRouter(
            probe_interval_s=999.0, registry=MetricRegistry()
        )
        http = router.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            code, out, _ = cli(
                "status", "--router-url",
                f"http://127.0.0.1:{http.port}",
            )
            assert code == 0
            assert "health: goodput=" in out
            assert "burn=" in out
            # the metrics scrape prints the federated shape
            assert "federation: replicas=none" in out
            assert "pio_slo_burn_rate" in out
        finally:
            router.close()
            http.shutdown()

    def test_profile_parser_flags(self):
        from predictionio_tpu.cli.main import build_parser

        args = build_parser().parse_args([
            "profile", "--url", "http://h:8000", "--out", "prof",
            "--duration-ms", "2500", "--access-key", "k",
        ])
        assert args.url == "http://h:8000"
        assert args.out == "prof"
        assert args.duration_ms == 2500.0
        assert args.access_key == "k"
        assert args.func.__name__ == "cmd_profile"

    @pytest.fixture()
    def profile_server(self):
        """A /debug/profile-shaped endpoint answering a tiny bundle."""
        import base64
        import io
        import tarfile

        from predictionio_tpu.serving.http import (
            HTTPServer,
            Response,
            Router,
        )

        manifest = {
            "id": "abc123",
            "durationS": 0.25,
            "files": ["manifest.json", "spans.json"],
        }
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            for name, payload in (
                ("manifest.json", json.dumps(manifest)),
                ("spans.json", '{"traceEvents": []}'),
            ):
                data = payload.encode()
                info = tarfile.TarInfo(f"profile-abc123/{name}")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        bundle = base64.b64encode(buf.getvalue()).decode()
        seen = {}

        def handler(request):
            seen["body"] = json.loads(request.body)
            seen["key"] = request.headers.get("X-PIO-Server-Key")
            return Response(
                200, {"profile": manifest, "bundle": bundle}
            )

        router = Router()
        router.route("POST", "/debug/profile", handler)
        http = HTTPServer(router, host="127.0.0.1", port=0)
        http.start()
        yield f"http://127.0.0.1:{http.port}", seen
        http.shutdown()

    def test_profile_pulls_and_extracts_bundle(
        self, cli, profile_server, tmp_path
    ):
        base, seen = profile_server
        out = tmp_path / "prof"
        code, stdout, _ = cli(
            "profile", "--url", base, "--out", str(out),
            "--duration-ms", "250", "--access-key", "sekrit",
        )
        assert code == 0
        assert seen["body"] == {"durationMs": 250.0}
        assert seen["key"] == "sekrit"
        assert "Wrote profile artifact abc123" in stdout
        extracted = out / "profile-abc123"
        assert json.loads((extracted / "manifest.json").read_text())[
            "id"
        ] == "abc123"
        assert (extracted / "spans.json").exists()

    def test_profile_rejects_non_bundle_payload(self, cli):
        from predictionio_tpu.serving.http import (
            HTTPServer,
            Response,
            Router,
        )

        router = Router()
        router.route(
            "POST", "/debug/profile", lambda r: Response(200, {})
        )
        http = HTTPServer(router, host="127.0.0.1", port=0)
        http.start()
        try:
            code, _, err = cli(
                "profile", "--url",
                f"http://127.0.0.1:{http.port}", "--out", "prof",
            )
            assert code == 1
            assert "did not answer a profile bundle" in err
        finally:
            http.shutdown()

    def test_safe_extract_rejects_traversal(self, tmp_path):
        import io
        import tarfile

        from predictionio_tpu.cli.main import _safe_extract

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            data = b"evil"
            info = tarfile.TarInfo("../escaped.txt")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        buf.seek(0)
        dest = tmp_path / "out"
        dest.mkdir()
        with tarfile.open(fileobj=buf, mode="r:gz") as tar:
            with pytest.raises((ValueError, tarfile.TarError)):
                _safe_extract(tar, str(dest))
        assert not (tmp_path / "escaped.txt").exists()


class TestPoolCLI:
    """ISSUE 17: the multi-tenant model-pool status line."""

    def test_pool_summary_line_formats(self):
        from predictionio_tpu.cli.main import _pool_summary_line

        line = _pool_summary_line(
            {
                "pio_pool_budget_bytes": {
                    "samples": [{"labels": {}, "value": 20000}]
                },
                "pio_pool_tenants_resident": {
                    "samples": [{"labels": {}, "value": 1}]
                },
                "pio_pool_resident_bytes": {
                    "samples": [
                        {"labels": {"tenant": "alice"}, "value": 16384}
                    ]
                },
                "pio_pool_hits_total": {
                    "samples": [
                        {"labels": {"tenant": "alice"}, "value": 7},
                        {"labels": {"tenant": "bob"}, "value": 0},
                    ]
                },
                "pio_pool_misses_total": {
                    "samples": [
                        {"labels": {"tenant": "alice"}, "value": 1}
                    ]
                },
                "pio_pool_evictions_total": {
                    "samples": [
                        {"labels": {"tenant": "bob"}, "value": 19}
                    ]
                },
            }
        )
        assert line == (
            "pool: tenantsResident=1 bytes=16384/20000 "
            "hitRate=0.88 evictions=19"
        )
        # no pool series scraped → no line (single-tenant server)
        assert _pool_summary_line({}) is None
        # a pool with no lookups yet omits the hit rate
        cold = _pool_summary_line(
            {
                "pio_pool_budget_bytes": {
                    "samples": [{"labels": {}, "value": 100}]
                }
            }
        )
        assert cold == "pool: tenantsResident=0 bytes=0/100 evictions=0"

    def test_status_metrics_url_prints_pool_line(self, cli):
        import sys as _sys

        _sys.path.insert(
            0, str(__import__("pathlib").Path(__file__).parent)
        )
        from pool_replica_child import build_replica

        from predictionio_tpu.obs import MetricRegistry

        server = build_replica(
            "gcli", budget_bytes=200_000, warmup=False,
            registry=MetricRegistry(),
        )
        http = server.serve(host="127.0.0.1", port=0)
        http.start()
        try:
            code, out, _ = cli(
                "status", "--metrics-url",
                f"http://127.0.0.1:{http.port}",
            )
            assert code == 0
            assert "pool: tenantsResident=" in out
            assert "pio_pool_budget_bytes" in out
        finally:
            server.close()
            # build_replica hands the server an externally-owned pool;
            # close it here or its loader + batcher threads outlive us
            server._pool.close()
            http.shutdown()
