"""Evaluation & tuning: Metric library + MetricEvaluator + Evaluation.

Capability parity with the reference:

* ``Metric`` hierarchy (controller/Metric.scala:36-266): Average /
  OptionAverage / Stdev / OptionStdev / Sum / Zero metrics over
  (evalInfo, query, prediction, actual) tuples. The reference computes
  these with Spark ``StatCounter``; here points are host floats (the
  heavy part — batch prediction — already ran on the mesh).
* ``MetricEvaluator`` (controller/MetricEvaluator.scala:182-259): scores
  every candidate EngineParams, tracks the best by the metric's
  ordering, optionally writes the winning variant JSON
  (``outputPath="best.json"``).
* ``Evaluation`` (controller/Evaluation.scala:31-122): engine + metric +
  params grid, the unit ``run_evaluation`` executes.
"""

from __future__ import annotations

import abc
import dataclasses
import json
import logging
import math
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Generic, Sequence, TypeVar

from predictionio_tpu.core.controller import params_to_json
from predictionio_tpu.core.engine import Engine, EngineParams, WorkflowParams
from predictionio_tpu.parallel.mesh import ComputeContext

logger = logging.getLogger(__name__)

R = TypeVar("R")

#: eval output shape: per fold, (evalInfo, [(query, prediction, actual)])
EvalData = Sequence[tuple[Any, Sequence[tuple[Any, Any, Any]]]]


def kfold_indices(n: int, k: int):
    """Index-modulo k-fold split (reference e2 CrossValidation.splitData,
    e2/.../evaluation/CrossValidation.scala:33-63): yields
    ``(fold, train_idx, test_idx)`` int arrays. The shared split used by
    every template's ``read_eval``."""
    import numpy as np

    if k <= 1:
        raise ValueError("eval_k must be >= 2 for evaluation")
    idx = np.arange(n)
    for fold in range(k):
        test = idx % k == fold
        yield fold, idx[~test], idx[test]


class Metric(abc.ABC, Generic[R]):
    """Score one engine-params candidate from its eval output."""

    #: ordering: larger is better (reference Metric's implicit Ordering)
    higher_is_better: bool = True

    @property
    def header(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def calculate(self, eval_data: EvalData) -> R: ...

    def compare(self, a: R, b: R) -> int:
        sign = 1 if self.higher_is_better else -1
        return sign * ((a > b) - (a < b))


class PointMetric(Metric[float]):
    """Base for per-(q, p, a) point metrics."""

    @abc.abstractmethod
    def calculate_point(self, eval_info, query, prediction, actual) -> (
        float | None
    ): ...

    def _points(self, eval_data: EvalData) -> list[float]:
        out = []
        for eval_info, qpa in eval_data:
            for q, p, a in qpa:
                point = self.calculate_point(eval_info, q, p, a)
                if point is not None:
                    out.append(float(point))
        return out


class AverageMetric(PointMetric):
    """Mean of points (reference AverageMetric; None points are an error
    in the reference — use OptionAverageMetric to skip)."""

    def calculate(self, eval_data: EvalData) -> float:
        points = self._points(eval_data)
        return sum(points) / len(points) if points else float("-inf")


class OptionAverageMetric(AverageMetric):
    """calculate_point may return None to exclude a point."""


class SumMetric(PointMetric):
    def calculate(self, eval_data: EvalData) -> float:
        return sum(self._points(eval_data))


class StdevMetric(PointMetric):
    higher_is_better = False

    def calculate(self, eval_data: EvalData) -> float:
        points = self._points(eval_data)
        if len(points) < 2:
            return 0.0
        mean = sum(points) / len(points)
        return math.sqrt(
            sum((x - mean) ** 2 for x in points) / len(points)
        )


class OptionStdevMetric(StdevMetric):
    pass


class ZeroMetric(Metric[float]):
    """Always 0 (reference ZeroMetric — placeholder for eval-only runs)."""

    def calculate(self, eval_data: EvalData) -> float:
        return 0.0


@dataclasses.dataclass
class MetricScores:
    score: Any
    other_scores: list[Any]


@dataclasses.dataclass
class MetricEvaluatorResult:
    """Reference MetricEvaluatorResult (MetricEvaluator.scala:61-107)."""

    best_score: MetricScores
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: list[str]
    engine_params_scores: list[tuple[EngineParams, MetricScores]]

    def to_one_liner(self) -> str:
        return (
            f"[{self.metric_header}] best: {self.best_score.score} "
            f"(candidate {self.best_idx + 1}/"
            f"{len(self.engine_params_scores)})"
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "bestScore": self.best_score.score,
                "bestIdx": self.best_idx,
                "bestEngineParams": _engine_params_json(
                    self.best_engine_params
                ),
                "scores": [
                    s.score for _p, s in self.engine_params_scores
                ],
            }
        )

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s.score}</td></tr>"
            for i, (_p, s) in enumerate(self.engine_params_scores)
        )
        return (
            f"<h3>{self.metric_header}</h3><p>best: "
            f"{self.best_score.score} (candidate {self.best_idx})</p>"
            f"<table>{rows}</table>"
        )


def _engine_params_json(params: EngineParams) -> dict:
    return {
        "datasource": {
            "name": params.data_source[0],
            "params": params_to_json(params.data_source[1]),
        },
        "preparator": {
            "name": params.preparator[0],
            "params": params_to_json(params.preparator[1]),
        },
        "algorithms": [
            {"name": n, "params": params_to_json(p)}
            for n, p in params.algorithms
        ],
        "serving": {
            "name": params.serving[0],
            "params": params_to_json(params.serving[1]),
        },
    }


class MetricEvaluator:
    """Score every candidate, pick the best (MetricEvaluator.scala:215-259).

    Candidates are evaluated concurrently (the reference's ``.par`` at
    MetricEvaluator.scala:224): threads suffice because the heavy work
    (train / batch-predict) runs inside XLA, which releases the GIL,
    and FastEvalEngine's caches are single-flight thread-safe.
    ``parallelism=1`` (or env ``PIO_EVAL_PARALLELISM=1``) forces the
    sequential path.
    """

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: str | None = None,
        parallelism: int | None = None,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path
        self.parallelism = parallelism

    def _eval_parallelism(self, n_candidates: int) -> int:
        if self.parallelism is not None:
            return max(1, self.parallelism)
        env = os.environ.get("PIO_EVAL_PARALLELISM", "")
        if env:
            return max(1, int(env))
        return min(4, n_candidates)

    def evaluate(
        self,
        ctx: ComputeContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
        workflow: WorkflowParams | None = None,
    ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        n = len(engine_params_list)
        workers = self._eval_parallelism(n)
        if workers > 1 and n > 1:
            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="pio-eval"
            ) as pool:
                eval_datas = list(
                    pool.map(
                        lambda p: engine.eval(ctx, p, workflow),
                        engine_params_list,
                    )
                )
        else:
            eval_datas = [
                engine.eval(ctx, p, workflow) for p in engine_params_list
            ]
        scores: list[tuple[EngineParams, MetricScores]] = []
        for i, (params, eval_data) in enumerate(
            zip(engine_params_list, eval_datas)
        ):
            score = MetricScores(
                score=self.metric.calculate(eval_data),
                other_scores=[
                    m.calculate(eval_data) for m in self.other_metrics
                ],
            )
            logger.info(
                "candidate %d/%d: %s = %s",
                i + 1,
                n,
                self.metric.header,
                score.score,
            )
            scores.append((params, score))
        best_idx = 0
        for i in range(1, len(scores)):
            if (
                self.metric.compare(
                    scores[i][1].score, scores[best_idx][1].score
                )
                > 0
            ):
                best_idx = i
        result = MetricEvaluatorResult(
            best_score=scores[best_idx][1],
            best_engine_params=scores[best_idx][0],
            best_idx=best_idx,
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            with open(self.output_path, "w") as f:
                json.dump(
                    _engine_params_json(result.best_engine_params),
                    f,
                    indent=2,
                )
            logger.info("best engine params written to %s", self.output_path)
        return result


@dataclasses.dataclass
class Evaluation:
    """Engine + metric + candidate grid (reference Evaluation.scala:31-122;
    the grid is a plain list — the EngineParamsGenerator equivalent is
    any callable producing it)."""

    engine: Engine
    metric: Metric
    engine_params_list: Sequence[EngineParams]
    other_metrics: Sequence[Metric] = ()
    output_path: str | None = None
    #: memoize pipeline prefixes across candidates (run_evaluation wraps
    #: plain Engines in FastEvalEngine); set False to force re-runs
    fast_eval: bool = True
    #: candidate-evaluation thread count (None → PIO_EVAL_PARALLELISM
    #: env or min(4, n_candidates))
    parallelism: int | None = None


#: EngineParamsGenerator (reference EngineParamsGenerator.scala:27-43)
EngineParamsGenerator = Callable[[], Sequence[EngineParams]]
