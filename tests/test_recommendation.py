"""Recommendation template end-to-end: events → ALS train → deploy →
top-k predictions (reference scala-parallel-recommendation quickstart)."""

import numpy as np
import pytest

from predictionio_tpu.core.engine import EngineParams
from predictionio_tpu.core.workflow import load_deployment, run_train
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSParams,
    RecDataSourceParams,
    RecPreparatorParams,
    recommendation_engine,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@pytest.fixture(scope="module")
def ctx():
    return ComputeContext.create(batch="rec-test")


def _seed(storage, n_users=24, n_items=16):
    """Two taste clusters: even users like even items, odd like odd."""
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="recapp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(0)
    for u in range(n_users):
        liked = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(liked, size=6, replace=False):
            events.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(rng.integers(3, 6))}),
                ),
                app_id,
            )
    return app_id


def _params(num_iterations=6, eval_k=0):
    return EngineParams(
        data_source=(
            "",
            RecDataSourceParams(app_name="recapp", eval_k=eval_k),
        ),
        preparator=("", RecPreparatorParams(dedupe="sum")),
        algorithms=[
            (
                "als",
                ALSParams(
                    rank=8,
                    num_iterations=num_iterations,
                    lambda_=0.05,
                    alpha=4.0,
                    block_len=8,
                    row_chunk=8,
                ),
            )
        ],
    )


class TestEndToEnd:
    def test_train_deploy_recommend(self, ctx, memory_storage):
        _seed(memory_storage)
        engine = recommendation_engine()
        run_train(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        _, algorithms, models, serving = load_deployment(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        algo, model = algorithms[0], models[0]
        result = serving.serve(
            {"user": "u0", "num": 5},
            [algo.predict(model, {"user": "u0", "num": 5})],
        )
        assert len(result["itemScores"]) == 5
        # u0 (even cluster) should be recommended mostly even items
        even = sum(
            1
            for s in result["itemScores"]
            if int(s["item"][1:]) % 2 == 0
        )
        assert even >= 4
        scores = [s["score"] for s in result["itemScores"]]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty(self, ctx, memory_storage):
        _seed(memory_storage)
        engine = recommendation_engine()
        run_train(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        _, algorithms, models, _ = load_deployment(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        assert algorithms[0].predict(
            models[0], {"user": "nobody", "num": 3}
        ) == {"itemScores": []}

    def test_batch_predict_mixed_nums(self, ctx, memory_storage):
        _seed(memory_storage)
        engine = recommendation_engine()
        run_train(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        _, algorithms, models, _ = load_deployment(
            engine, _params(), engine_id="rec", ctx=ctx,
            storage=memory_storage,
        )
        out = algorithms[0].batch_predict(
            models[0],
            [
                {"user": "u1", "num": 2},
                {"user": "u2", "num": 7},
            ],
        )
        assert len(out[0]["itemScores"]) == 2
        assert len(out[1]["itemScores"]) == 7

    def test_eval_ranking(self, ctx, memory_storage):
        """Held-out items should rank well (precision proxy)."""
        _seed(memory_storage)
        engine = recommendation_engine()
        results = engine.eval(ctx, _params(eval_k=3))
        hits = total = 0
        for _info, qpa in results:
            for _q, p, actual in qpa:
                recommended = {s["item"] for s in p["itemScores"]}
                hits += len(recommended & set(actual))
                total += len(actual)
        assert total > 0
        assert hits / total > 0.5
