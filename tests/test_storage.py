"""Storage contract suite — run against every backend
(reference LEventsSpec/PEventsSpec pattern: one contract, N backends,
data/src/test/.../LEventsSpec.scala:22-49)."""

import datetime as dt

import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    Storage,
    StorageError,
)


def _t(seconds: int) -> dt.datetime:
    return dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(
        seconds=seconds
    )


@pytest.fixture(
    params=["memory", "sqlite", "eventlog", "postgres", "mysql",
            "httpstore"]
)
def storage(request):
    # lazy lookup: only the backend under test is built — the socket
    # backends (postgres/mysql/httpstore) boot a real server per use
    return request.getfixturevalue(f"{request.param}_storage")


class TestApps:
    def test_crud(self, storage):
        apps = storage.get_meta_data_apps()
        app_id = apps.insert(App(id=0, name="myapp", description="d"))
        assert app_id is not None and app_id > 0
        assert apps.get(app_id).name == "myapp"
        assert apps.get_by_name("myapp").id == app_id
        # duplicate name rejected
        assert apps.insert(App(id=0, name="myapp")) is None
        assert apps.update(App(id=app_id, name="myapp2")) is True
        assert apps.get_by_name("myapp2") is not None
        assert [a.id for a in apps.get_all()] == [app_id]
        assert apps.delete(app_id) is True
        assert apps.get(app_id) is None


class TestAccessKeys:
    def test_crud(self, storage):
        keys = storage.get_meta_data_access_keys()
        k = keys.insert(AccessKey(key="", appid=1, events=("view",)))
        assert k and len(k) > 20
        got = keys.get(k)
        assert got.appid == 1 and got.events == ("view",)
        assert keys.get_by_app_id(1) == [got]
        assert keys.get_by_app_id(2) == []
        assert keys.delete(k) is True
        assert keys.get(k) is None


class TestChannels:
    def test_crud_and_name_validation(self, storage):
        channels = storage.get_meta_data_channels()
        cid = channels.insert(Channel(id=0, name="ch-1", appid=1))
        assert cid is not None
        assert channels.get(cid).name == "ch-1"
        assert channels.insert(Channel(id=0, name="bad name!", appid=1)) is None
        assert (
            channels.insert(Channel(id=0, name="x" * 17, appid=1)) is None
        )
        assert [c.id for c in channels.get_by_app_id(1)] == [cid]
        assert channels.delete(cid) is True


class TestEngineInstances:
    def test_lifecycle(self, storage):
        eis = storage.get_meta_data_engine_instances()
        base = dict(
            engine_id="e",
            engine_version="1",
            engine_variant="v",
            engine_factory="f",
        )
        a = eis.insert(
            EngineInstance(
                id="", status="INIT", start_time=_t(0), end_time=_t(0), **base
            )
        )
        b = eis.insert(
            EngineInstance(
                id="",
                status="COMPLETED",
                start_time=_t(10),
                end_time=_t(20),
                **base,
            )
        )
        c = eis.insert(
            EngineInstance(
                id="",
                status="COMPLETED",
                start_time=_t(30),
                end_time=_t(40),
                **base,
            )
        )
        assert len({a, b, c}) == 3
        latest = eis.get_latest_completed("e", "1", "v")
        assert latest.id == c
        inst = eis.get(a)
        assert eis.update(
            EngineInstance(**{**inst.__dict__, "status": "FAILED"})
        )
        assert eis.get(a).status == "FAILED"
        assert eis.get_latest_completed("e", "1", "other") is None
        assert eis.delete(a)


class TestEngineManifests:
    def test_crud(self, storage):
        manifests = storage.get_meta_data_engine_manifests()
        m = EngineManifest(
            id="rec",
            version="1.0",
            name="recommendation",
            description="ALS engine",
            files=("/tmp/engine.json",),
            engine_factory="predictionio_tpu.models.recommendation:factory",
        )
        manifests.insert(m)
        got = manifests.get("rec", "1.0")
        assert got == m
        assert manifests.get("rec", "2.0") is None
        assert manifests.get_all() == [m]
        # update requires existence unless upsert
        with pytest.raises(KeyError):
            manifests.update(
                EngineManifest(id="other", version="1.0", name="x")
            )
        manifests.update(
            EngineManifest(id="other", version="1.0", name="x"), upsert=True
        )
        assert len(manifests.get_all()) == 2
        assert manifests.delete("rec", "1.0") is True
        assert manifests.delete("rec", "1.0") is False
        assert manifests.get("rec", "1.0") is None


class TestEvaluationInstances:
    def test_lifecycle(self, storage):
        evis = storage.get_meta_data_evaluation_instances()
        i = evis.insert(
            EvaluationInstance(
                id="", status="INIT", start_time=_t(0), end_time=_t(0)
            )
        )
        inst = evis.get(i)
        assert inst.status == "INIT"
        assert evis.update(
            EvaluationInstance(
                **{
                    **inst.__dict__,
                    "status": "EVALCOMPLETED",
                    "evaluator_results": "best!",
                }
            )
        )
        assert evis.get_completed()[0].evaluator_results == "best!"


class TestModels:
    def test_blob_roundtrip(self, storage):
        models = storage.get_model_data_models()
        models.insert(Model(id="m1", models=b"\x00\x01\x02"))
        assert models.get("m1").models == b"\x00\x01\x02"
        # overwrite
        models.insert(Model(id="m1", models=b"\x03"))
        assert models.get("m1").models == b"\x03"
        assert models.delete("m1") is True
        assert models.get("m1") is None


class TestEvents:
    def _seed(self, events, app_id, channel_id=None):
        events.init(app_id, channel_id)
        out = []
        for i in range(10):
            out.append(
                events.insert(
                    Event(
                        event="view" if i % 2 == 0 else "buy",
                        entity_type="user",
                        entity_id=f"u{i % 3}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"n": i}),
                        event_time=_t(i),
                    ),
                    app_id,
                    channel_id,
                )
            )
        return out

    def test_insert_get_delete(self, storage):
        events = storage.get_events()
        ids = self._seed(events, 1)
        e = events.get(ids[0], 1)
        assert e.event == "view" and e.properties.get_int("n") == 0
        assert events.delete(ids[0], 1) is True
        assert events.get(ids[0], 1) is None
        assert events.delete(ids[0], 1) is False

    def test_find_filters(self, storage):
        events = storage.get_events()
        self._seed(events, 1)
        assert len(list(events.find(1))) == 10
        assert len(list(events.find(1, event_names=["view"]))) == 5
        assert len(list(events.find(1, entity_id="u0"))) == 4
        assert (
            len(list(events.find(1, start_time=_t(3), until_time=_t(7))))
            == 4
        )
        got = list(events.find(1, limit=3))
        assert [e.event_time for e in got] == [_t(0), _t(1), _t(2)]
        got = list(events.find(1, limit=3, reversed=True))
        assert got[0].event_time == _t(9)
        # tri-state target filter
        assert len(list(events.find(1, target_entity_id="i4"))) == 1
        events.insert(
            Event(
                event="$set",
                entity_type="user",
                entity_id="u9",
                event_time=_t(100),
            ),
            1,
        )
        assert len(list(events.find(1, target_entity_id=None))) == 1

    def test_channels_isolated(self, storage):
        events = storage.get_events()
        self._seed(events, 1)
        self._seed(events, 1, channel_id=7)
        events.insert(
            Event(event="extra", entity_type="u", entity_id="x"),
            1,
            7,
        )
        assert len(list(events.find(1))) == 10
        assert len(list(events.find(1, 7))) == 11

    def test_remove(self, storage):
        events = storage.get_events()
        self._seed(events, 2)
        assert events.remove(2) is True
        assert list(events.find(2)) == []

    def test_aggregate_via_backend(self, storage):
        events = storage.get_events()
        events.init(3)
        events.insert(
            Event(
                event="$set",
                entity_type="item",
                entity_id="i1",
                properties=DataMap({"color": "red"}),
                event_time=_t(0),
            ),
            3,
        )
        events.insert(
            Event(
                event="$set",
                entity_type="item",
                entity_id="i1",
                properties=DataMap({"color": "blue"}),
                event_time=_t(5),
            ),
            3,
        )
        events.insert(
            Event(
                event="$set",
                entity_type="user",
                entity_id="u1",
                properties=DataMap({"x": 1}),
                event_time=_t(0),
            ),
            3,
        )
        props = events.aggregate_properties(3, entity_type="item")
        assert set(props) == {"i1"}
        assert props["i1"]["color"] == "blue"


class TestRegistry:
    def test_unknown_backend_type_raises(self):
        with pytest.raises(StorageError):
            Storage(env={"PIO_STORAGE_SOURCES_X_TYPE": "nope"})

    def test_unbound_repo_binding_raises(self):
        with pytest.raises(StorageError):
            Storage(
                env={
                    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "OTHER",
                }
            )

    def test_verify_all_data_objects(self, storage):
        assert storage.verify_all_data_objects() == []

    def test_models_only_source_rejects_events(self, tmp_path):
        storage = Storage(
            env={
                "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
                "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path),
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            }
        )
        with pytest.raises(StorageError):
            storage.get_events()


class TestLegacySchemaMigration:
    def test_access_key_column_renamed_in_place(self, tmp_path):
        """Databases created before the MySQL dialect had
        ``access_keys.key``; opening them must migrate, not break."""
        import sqlite3

        path = str(tmp_path / "legacy.sqlite")
        conn = sqlite3.connect(path)
        conn.execute(
            "CREATE TABLE access_keys ("
            "key TEXT PRIMARY KEY, appid INTEGER NOT NULL, "
            "events TEXT NOT NULL)"
        )
        conn.execute(
            "INSERT INTO access_keys VALUES ('legacy-key', 7, '[]')"
        )
        conn.commit()
        conn.close()
        storage = Storage(
            env={
                "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQL_PATH": path,
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            }
        )
        keys = storage.get_meta_data_access_keys()
        got = keys.get("legacy-key")
        assert got is not None and got.appid == 7


class TestReviewRegressions:
    """Regression tests for the round-1 code-review findings."""

    def test_naive_datetime_bounds_are_utc(self, storage):
        events = storage.get_events()
        events.init(9)
        events.insert(
            Event(
                event="view",
                entity_type="user",
                entity_id="u1",
                event_time=_t(100),
            ),
            9,
        )
        naive = dt.datetime(2020, 1, 1)  # == _t(0) under naive-is-UTC
        got = list(events.find(9, start_time=naive))
        assert len(got) == 1

    def test_sqlite_insert_auto_inits_table(self, sqlite_storage):
        events = sqlite_storage.get_events()
        # no init() call — must auto-create like the memory backend
        eid = events.insert(
            Event(event="view", entity_type="user", entity_id="u1"), 77
        )
        assert events.get(eid, 77) is not None

    def test_aggregate_requires_entity_type(self, storage):
        events = storage.get_events()
        events.init(8)
        with pytest.raises(TypeError):
            events.aggregate_properties(8)  # positional-only misuse
        with pytest.raises(ValueError):
            events.aggregate_properties(8, entity_type="")

    def test_unbound_repo_with_multiple_sources_raises(self):
        storage = Storage(
            env={
                "PIO_STORAGE_SOURCES_A_TYPE": "memory",
                "PIO_STORAGE_SOURCES_B_TYPE": "memory",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "A",
            }
        )
        with pytest.raises(StorageError):
            storage.get_meta_data_apps()
        # bound repo still works
        storage.get_events()
