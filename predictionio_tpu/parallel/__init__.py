"""Parallel substrate: device mesh, sharding helpers, collectives, multi-host.

This package replaces the reference's L3 compute backend (Apache Spark:
SparkContext + RDD + shuffle/broadcast, SURVEY.md §1 L3, §2.9) with the
JAX equivalents: an explicit :class:`ComputeContext` wrapping a
``jax.sharding.Mesh``, NamedSharding annotations instead of RDD
partitioning, and XLA collectives (psum / all_gather / reduce_scatter over
ICI) instead of Netty shuffles.
"""

from predictionio_tpu.parallel.mesh import (
    ComputeContext,
    DATA_AXIS,
    MODEL_AXIS,
    assert_phantom_rows_zero,
    pad_to_multiple,
)
from predictionio_tpu.parallel.partition import (
    als_partition_rules,
    match_partition_rule,
    match_partition_rules,
    mesh_from_topology,
    shard_pytree,
    stage_factor_matrix,
    topology_mesh_shape,
    validate_rules,
)

__all__ = [
    "ComputeContext",
    "DATA_AXIS",
    "MODEL_AXIS",
    "assert_phantom_rows_zero",
    "pad_to_multiple",
    "als_partition_rules",
    "match_partition_rule",
    "match_partition_rules",
    "mesh_from_topology",
    "shard_pytree",
    "stage_factor_matrix",
    "topology_mesh_shape",
    "validate_rules",
]
