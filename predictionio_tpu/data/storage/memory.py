"""In-memory storage backend (tests / dev; reference's closest analogue is
the inline mock DAOs used by its HTTP specs, SegmentIOAuthSpec.scala:21-57).

Implements every DAO interface with plain dicts behind one lock, so a full
app → events → train → deploy cycle can run with zero external services.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import itertools
import threading
import uuid
from typing import Iterator, Sequence

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeysBackend,
    App,
    AppsBackend,
    Channel,
    ChannelsBackend,
    EngineInstance,
    EngineInstancesBackend,
    EngineManifest,
    EngineManifestsBackend,
    EvaluationInstance,
    EvaluationInstancesBackend,
    EventsBackend,
    Model,
    ModelsBackend,
)


class MemoryApps(AppsBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._apps: dict[int, App] = {}
        self._next = itertools.count(1)

    def insert(self, app: App) -> int | None:
        with self._lock:
            app_id = app.id if app.id > 0 else next(self._next)
            if app_id in self._apps:
                return None
            if any(a.name == app.name for a in self._apps.values()):
                return None
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> App | None:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> App | None:
        with self._lock:
            return next(
                (a for a in self._apps.values() if a.name == name), None
            )

    def get_all(self) -> list[App]:
        return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._lock:
            if app.id not in self._apps:
                return False
            self._apps[app.id] = app
            return True

    def delete(self, app_id: int) -> bool:
        with self._lock:
            return self._apps.pop(app_id, None) is not None


class MemoryAccessKeys(AccessKeysBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._keys: dict[str, AccessKey] = {}

    def insert(self, access_key: AccessKey) -> str | None:
        with self._lock:
            key = access_key.key or self.generate_key()
            if key in self._keys:
                return None
            self._keys[key] = AccessKey(
                key, access_key.appid, tuple(access_key.events)
            )
            return key

    def get(self, key: str) -> AccessKey | None:
        return self._keys.get(key)

    def get_all(self) -> list[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [k for k in self._keys.values() if k.appid == app_id]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock:
            if access_key.key not in self._keys:
                return False
            self._keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._keys.pop(key, None) is not None


class MemoryChannels(ChannelsBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._channels: dict[int, Channel] = {}
        self._next = itertools.count(1)

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._lock:
            cid = channel.id if channel.id > 0 else next(self._next)
            if cid in self._channels:
                return None
            if any(
                c.appid == channel.appid and c.name == channel.name
                for c in self._channels.values()
            ):
                return None
            self._channels[cid] = Channel(cid, channel.name, channel.appid)
            return cid

    def get(self, channel_id: int) -> Channel | None:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [c for c in self._channels.values() if c.appid == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._lock:
            return self._channels.pop(channel_id, None) is not None


class MemoryEngineInstances(EngineInstancesBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._instances: dict[str, EngineInstance] = {}

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            self._instances[iid] = dataclasses.replace(instance, id=iid)
            return iid

    def get(self, instance_id: str) -> EngineInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EngineInstance]:
        return list(self._instances.values())

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        out = [
            i
            for i in self._instances.values()
            if i.status == "COMPLETED"
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(
            engine_id, engine_version, engine_variant
        )
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = instance
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryEngineManifests(EngineManifestsBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._manifests: dict[tuple[str, str], EngineManifest] = {}

    def insert(self, manifest: EngineManifest) -> None:
        with self._lock:
            self._manifests[(manifest.id, manifest.version)] = manifest

    def get(self, manifest_id: str, version: str) -> EngineManifest | None:
        return self._manifests.get((manifest_id, version))

    def get_all(self) -> list[EngineManifest]:
        return list(self._manifests.values())

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        with self._lock:
            key = (manifest.id, manifest.version)
            if key not in self._manifests and not upsert:
                raise KeyError(f"engine manifest {key} not found")
            self._manifests[key] = manifest

    def delete(self, manifest_id: str, version: str) -> bool:
        with self._lock:
            return (
                self._manifests.pop((manifest_id, version), None) is not None
            )


class MemoryEvaluationInstances(EvaluationInstancesBackend):
    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._instances: dict[str, EvaluationInstance] = {}

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            self._instances[iid] = dataclasses.replace(instance, id=iid)
            return iid

    def get(self, instance_id: str) -> EvaluationInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EvaluationInstance]:
        return list(self._instances.values())

    def get_completed(self) -> list[EvaluationInstance]:
        out = [
            i
            for i in self._instances.values()
            if i.status == "EVALCOMPLETED"
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EvaluationInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = instance
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryModels(ModelsBackend):
    def __init__(self, config=None):
        self._models: dict[str, Model] = {}

    def insert(self, model: Model) -> None:
        self._models[model.id] = model

    def get(self, model_id: str) -> Model | None:
        return self._models.get(model_id)

    def delete(self, model_id: str) -> bool:
        return self._models.pop(model_id, None) is not None

    def list_ids(self) -> list[str] | None:
        return sorted(self._models)


class MemoryEvents(EventsBackend):
    """Per-(app, channel) ordered event lists behind one lock."""

    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._store: dict[tuple[int, int | None], dict[str, Event]] = {}

    def _key(self, app_id: int, channel_id: int | None):
        return (app_id, channel_id)

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            self._store.setdefault(self._key(app_id, channel_id), {})
            return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            return (
                self._store.pop(self._key(app_id, channel_id), None)
                is not None
            )

    def close(self) -> None:
        pass

    def insert(
        self, event: Event, app_id: int, channel_id: int | None = None
    ) -> str:
        stamped = event.with_id(event.event_id)
        with self._lock:
            table = self._store.setdefault(self._key(app_id, channel_id), {})
            table[stamped.event_id] = stamped
        return stamped.event_id

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        return self._store.get(self._key(app_id, channel_id), {}).get(
            event_id
        )

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        with self._lock:
            table = self._store.get(self._key(app_id, channel_id), {})
            return table.pop(event_id, None) is not None

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        with self._lock:
            events = list(
                self._store.get(self._key(app_id, channel_id), {}).values()
            )
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        # Naive bounds are UTC by convention (same rule as Event.__post_init__)
        if start_time is not None and start_time.tzinfo is None:
            start_time = start_time.replace(tzinfo=_dt.timezone.utc)
        if until_time is not None and until_time.tzinfo is None:
            until_time = until_time.replace(tzinfo=_dt.timezone.utc)
        names = set(event_names) if event_names is not None else None
        if limit is not None and limit == 0:
            return
        n = 0
        for e in events:
            if start_time is not None and e.event_time < start_time:
                continue
            if until_time is not None and e.event_time >= until_time:
                continue
            if entity_type is not None and e.entity_type != entity_type:
                continue
            if entity_id is not None and e.entity_id != entity_id:
                continue
            if names is not None and e.event not in names:
                continue
            if target_entity_type is not ... and (
                e.target_entity_type != target_entity_type
            ):
                continue
            if target_entity_id is not ... and (
                e.target_entity_id != target_entity_id
            ):
                continue
            yield e
            n += 1
            if limit is not None and 0 < limit <= n:
                return
