"""Parallel substrate: device mesh, sharding helpers, collectives, multi-host.

This package replaces the reference's L3 compute backend (Apache Spark:
SparkContext + RDD + shuffle/broadcast, SURVEY.md §1 L3, §2.9) with the
JAX equivalents: an explicit :class:`ComputeContext` wrapping a
``jax.sharding.Mesh``, NamedSharding annotations instead of RDD
partitioning, and XLA collectives (psum / all_gather / reduce_scatter over
ICI) instead of Netty shuffles.
"""

from predictionio_tpu.parallel.mesh import (
    ComputeContext,
    DATA_AXIS,
    MODEL_AXIS,
    pad_to_multiple,
)

__all__ = [
    "ComputeContext",
    "DATA_AXIS",
    "MODEL_AXIS",
    "pad_to_multiple",
]
