"""Pipelined micro-batching: two-phase dispatch, failure modes, the
adaptive coalescing window, and the single-phase compatibility path
(docs/serving.md "Pipelined dispatch").

The pipeline's invariants under failure matter more than its happy
path: a dispatch-stage error must only poison its own batch (the one
already enqueued behind it still resolves), close() must drain
in-flight dispatches in order, and cancellation racing the
collector→dispatch handoff must end in exactly one terminal state.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout

import pytest

from predictionio_tpu.obs import MetricRegistry
from predictionio_tpu.serving import resilience
from predictionio_tpu.serving.batching import (
    BatcherOverloaded,
    MicroBatcher,
    TwoPhaseBatchFn,
)


class _TwoPhase:
    """Scriptable two-phase batch_fn: blockable collect, per-batch
    dispatch failure injection, full call logs."""

    def __init__(self):
        self.release = threading.Event()
        self.release.set()
        self.dispatched: list[list] = []
        self.collected: list[list] = []
        self.lock = threading.Lock()

    def dispatch(self, items):
        if items and items[0] == "boom-dispatch":
            raise ValueError("injected dispatch failure")
        with self.lock:
            self.dispatched.append(list(items))
        return list(items)

    def collect(self, handle):
        if not self.release.wait(timeout=10):
            raise RuntimeError("collect never released")
        if handle and handle[0] == "boom-collect":
            raise ValueError("injected collect failure")
        with self.lock:
            self.collected.append(list(handle))
        return [str(i).upper() for i in handle]


class TestTwoPhaseProtocol:
    def test_results_in_order_through_both_stages(self):
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=4, max_wait_ms=5,
        )
        try:
            futures = [b.submit(f"q{i}") for i in range(10)]
            assert [f.result(5) for f in futures] == [
                f"Q{i}" for i in range(10)
            ]
            assert sum(len(d) for d in fn.dispatched) == 10
            assert fn.dispatched == fn.collected
        finally:
            b.close()

    def test_enqueue_overlaps_inflight_collect(self):
        """The pipelining claim itself: batch B's dispatch happens
        while batch A is still inside collect. Proved by deadlock
        avoidance — A's collect only unblocks once B has dispatched,
        so a serial batcher would hang here."""
        b_dispatched = threading.Event()

        class Fn:
            def dispatch(self, items):
                if items[0] == "b":
                    b_dispatched.set()
                return items

            def collect(self, handle):
                if handle[0] == "a":
                    assert b_dispatched.wait(timeout=5), (
                        "batch B never dispatched while A was in "
                        "collect — the stages are not overlapping"
                    )
                return [i * 2 for i in handle]

        fn = Fn()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=2,
        )
        try:
            fa = b.submit("a")
            fb = b.submit("b")
            assert fa.result(5) == "aa"
            assert fb.result(5) == "bb"
        finally:
            b.close()

    def test_pipeline_depth_bounds_inflight(self):
        """No more than pipeline_depth batches may sit between
        dispatch and collected results."""
        inflight = []
        peak = []
        lock = threading.Lock()
        gate = threading.Event()

        class Fn:
            def dispatch(self, items):
                with lock:
                    inflight.append(1)
                    peak.append(len(inflight))
                return items

            def collect(self, handle):
                gate.wait(10)
                with lock:
                    inflight.pop()
                return handle

        fn = Fn()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=2,
        )
        try:
            futures = [b.submit(i) for i in range(6)]
            time.sleep(0.3)  # give the collector every chance to overrun
            assert max(peak) <= 2
            gate.set()
            for f in futures:
                f.result(5)
            assert max(peak) <= 2
        finally:
            gate.set()
            b.close()


class TestPipelineFailureModes:
    def test_dispatch_raise_with_next_batch_enqueued(self):
        """A dispatch-stage error while another batch is already in
        flight: the failed batch's futures get the error immediately,
        the in-flight batch still resolves normally."""
        fn = _TwoPhase()
        fn.release.clear()  # hold batch A inside collect
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=2,
        )
        try:
            fa = b.submit("a")
            # wait until A is dispatched (in flight, uncollected)
            deadline = time.monotonic() + 5
            while not fn.dispatched and time.monotonic() < deadline:
                time.sleep(0.005)
            assert fn.dispatched == [["a"]]
            fboom = b.submit("boom-dispatch")
            with pytest.raises(ValueError, match="injected dispatch"):
                fboom.result(5)  # fails while A is STILL blocked
            assert not fa.done()
            fn.release.set()
            assert fa.result(5) == "A"
        finally:
            fn.release.set()
            b.close()

    def test_collect_raise_only_poisons_its_batch(self):
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=2,
        )
        try:
            fboom = b.submit("boom-collect")
            fok = b.submit("ok")
            with pytest.raises(ValueError, match="injected collect"):
                fboom.result(5)
            assert fok.result(5) == "OK"
        finally:
            b.close()

    def test_close_during_inflight_dispatch(self):
        """close() while a batch is inside collect: the batch resolves,
        both threads join, nothing leaks."""
        registry = MetricRegistry()
        fn = _TwoPhase()
        fn.release.clear()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=2,
            registry=registry, name="closing",
        )
        f1 = b.submit("x")
        f2 = b.submit("y")  # queued behind the blocked collect
        closed = threading.Event()

        def close():
            b.close()
            closed.set()

        t = threading.Thread(target=close)
        t.start()
        time.sleep(0.1)
        assert not closed.is_set()  # close is draining, not abandoning
        fn.release.set()
        t.join(timeout=10)
        assert closed.is_set()
        assert f1.result(1) == "X"
        assert f2.result(1) == "Y"
        leaked = registry.counter(
            "pio_batcher_leaked_threads_total", "", ("batcher",)
        ).labels("closing")
        assert leaked.value == 0

    def test_deadline_expiring_during_backpressure_wait_is_honored(self):
        """A budget that dies while the collector is blocked on the
        pipeline-depth semaphore must still drop the slot before the
        device sees it — the cutoff is the last word before dispatch."""
        gate = threading.Event()
        dispatched = []

        class Fn:
            def dispatch(self, items):
                dispatched.append(list(items))
                return items

            def collect(self, handle):
                gate.wait(10)
                return list(handle)

        b = MicroBatcher(
            TwoPhaseBatchFn(Fn().dispatch, Fn().collect),
            max_batch=1, max_wait_ms=0.1, pipeline_depth=1,
        )
        try:
            fa = b.submit("a")  # occupies the only pipeline slot
            deadline = time.monotonic() + 5
            while not dispatched and time.monotonic() < deadline:
                time.sleep(0.005)
            resilience.set_deadline(resilience.Deadline.after(0.15))
            fb = b.submit("b")
            resilience.set_deadline(None)
            time.sleep(0.4)  # budget dies while collector waits on slot
            gate.set()
            assert fa.result(5) == "a"
            with pytest.raises(resilience.DeadlineExceeded):
                fb.result(5)
            assert dispatched == [["a"]]  # "b" never reached the device
        finally:
            resilience.set_deadline(None)
            gate.set()
            b.close()

    def test_cancel_racing_the_handoff(self):
        """cancel() racing the collector→dispatch handoff: every
        future ends in exactly one terminal state, and a won cancel
        means the item NEVER reached dispatch."""
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=2, max_wait_ms=0.5, pipeline_depth=2,
        )
        try:
            outcomes = {"cancelled": 0, "served": 0}
            for i in range(60):
                f = b.submit(f"r{i}")
                if i % 2:
                    time.sleep(0.0005)  # land some cancels mid-handoff
                won = f.cancel()
                if won:
                    outcomes["cancelled"] += 1
                    assert f.cancelled()
                else:
                    assert f.result(5) == f"R{i}"
                    outcomes["served"] += 1
            with fn.lock:
                dispatched = [i for batch in fn.dispatched for i in batch]
            # a won cancel is a promise the device never saw the item
            assert len(dispatched) == outcomes["served"]
            assert outcomes["cancelled"] + outcomes["served"] == 60
        finally:
            b.close()


class TestSinglePhaseCompat:
    def test_zero_extra_barriers_exactly_one_call_per_batch(self):
        """The compat path must not add barriers around a plain
        batch_fn: exactly one call per dispatched batch, no wrapper
        invocations, counts matching pio_batches_total."""
        registry = MetricRegistry()
        calls: list[list] = []

        def batch_fn(items):
            calls.append(list(items))
            return [i * 2 for i in items]

        b = MicroBatcher(
            batch_fn, max_batch=8, max_wait_ms=5,
            registry=registry, name="compat",
        )
        try:
            futures = [b.submit(i) for i in range(24)]
            assert [f.result(5) for f in futures] == [
                i * 2 for i in range(24)
            ]
        finally:
            b.close()
        batches = registry.counter(
            "pio_batches_total", "", ("batcher",)
        ).labels("compat").value
        assert len(calls) == batches
        assert sum(len(c) for c in calls) == 24

    def test_serial_depth_zero_still_works(self):
        calls = []

        def batch_fn(items):
            calls.append(list(items))
            return [i + 1 for i in items]

        b = MicroBatcher(
            batch_fn, max_batch=4, max_wait_ms=1, pipeline_depth=0,
        )
        try:
            futures = [b.submit(i) for i in range(9)]
            assert [f.result(5) for f in futures] == [
                i + 1 for i in range(9)
            ]
            assert sum(len(c) for c in calls) == 9
        finally:
            b.close()


class TestAdaptiveWait:
    def test_full_batch_shrinks_wait_idle_restores_it(self):
        release = threading.Event()
        release.set()
        b = MicroBatcher(
            lambda items: list(items), max_batch=2, max_wait_ms=50,
        )
        try:
            full = b._max_wait  # seconds
            assert b._current_wait == full
            # a full batch must shrink the next window
            fs = [b.submit(1), b.submit(2)]
            [f.result(5) for f in fs]
            deadline = time.monotonic() + 2
            while b._current_wait >= full and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b._current_wait < full
            # a partial (idle-traffic) batch restores it
            b.submit(3).result(5)
            deadline = time.monotonic() + 2
            while b._current_wait != full and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b._current_wait == full
        finally:
            b.close()

    def test_adaptive_off_keeps_the_window(self):
        b = MicroBatcher(
            lambda items: list(items), max_batch=2, max_wait_ms=50,
            adaptive_wait=False,
        )
        try:
            fs = [b.submit(1), b.submit(2)]
            [f.result(5) for f in fs]
            b.submit(3).result(5)
            assert b._current_wait == b._max_wait
        finally:
            b.close()


class TestPipelineTelemetry:
    def test_enqueue_and_sync_histograms_recorded(self):
        registry = MetricRegistry()
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=4, max_wait_ms=2, registry=registry, name="tele",
        )
        try:
            futures = [b.submit(i) for i in range(8)]
            [f.result(5) for f in futures]
        finally:
            b.close()
        data = registry.to_dict()
        for metric in (
            "pio_device_enqueue_seconds",
            "pio_device_sync_seconds",
            "pio_device_dispatch_seconds",
        ):
            [sample] = [
                s for s in data[metric]["samples"]
                if s["labels"] == {"batcher": "tele"}
            ]
            assert sample["count"] >= 1, metric
        # end-to-end dispatch time covers both phases
        total = data["pio_device_dispatch_seconds"]["samples"][0]["sum"]
        enq = data["pio_device_enqueue_seconds"]["samples"][0]["sum"]
        assert total >= enq


class TestCallDeadlineCap:
    def test_call_timeout_capped_by_context_deadline(self):
        """MicroBatcher.__call__ must not wait its full default 30 s
        when the admitting request's budget is smaller."""
        gate = threading.Event()
        b = MicroBatcher(
            lambda items: (gate.wait(10), list(items))[1],
            max_batch=1, max_wait_ms=0.1,
        )
        resilience.set_deadline(resilience.Deadline.after(0.3))
        try:
            t0 = time.perf_counter()
            with pytest.raises(FuturesTimeout):
                b({"q": 1})  # default timeout would be 30 s
            assert time.perf_counter() - t0 < 2.0
        finally:
            resilience.set_deadline(None)
            gate.set()
            b.close()

    def test_call_without_deadline_keeps_explicit_timeout(self):
        b = MicroBatcher(
            lambda items: [i * 2 for i in items],
            max_batch=1, max_wait_ms=0.1,
        )
        try:
            assert b(21, timeout=5) == 42
        finally:
            b.close()


class TestOverloadClassAwareQueue:
    """Criticality-aware eviction at the queue bound and deadline-aware
    batch selection (docs/robustness.md "Overload & backpressure")."""

    def _shed_count(self, registry, name, cls):
        for s in registry.to_dict().get(
            "pio_shed_total", {}
        ).get("samples", []):
            if s["labels"] == {"batcher": name, "class": cls}:
                return s["value"]
        return 0.0

    def test_higher_class_evicts_lowest_and_counts_shed_class(self):
        from predictionio_tpu.serving import admission

        registry = MetricRegistry()
        fn = _TwoPhase()
        fn.release.clear()  # hold the pipeline: batches park in collect
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=1, max_queue=2,
            pipeline_depth=1, registry=registry, name="evict",
        )
        try:
            f_w1 = b.submit("w1")  # dispatched, stuck in collect
            time.sleep(0.1)
            f_w2 = b.submit("w2")  # taken by the collector, waiting
            time.sleep(0.1)       # on the pipeline slot
            with admission.criticality(admission.SHEDDABLE):
                f_s1 = b.submit("s1")
                f_s2 = b.submit("s2")
            # the queue is at its bound (2): a critical submission
            # evicts a sheddable slot instead of being refused
            with admission.criticality(admission.CRITICAL):
                f_c1 = b.submit("c1")
            evicted = [f for f in (f_s1, f_s2) if f.done()]
            assert len(evicted) == 1
            with pytest.raises(BatcherOverloaded):
                evicted[0].result(0)
            assert self._shed_count(registry, "evict", "sheddable") == 1
            # equal class cannot evict: the bound refuses it, counted
            # against ITS class
            with admission.criticality(admission.CRITICAL):
                b.submit("c2")  # evicts the remaining sheddable
                with pytest.raises(BatcherOverloaded):
                    b.submit("c3")
            assert self._shed_count(registry, "evict", "sheddable") == 2
            assert self._shed_count(registry, "evict", "critical") == 1
            fn.release.set()
            # everything still queued is served
            assert f_w1.result(10) == "W1"
            assert f_w2.result(10) == "W2"
            assert f_c1.result(10) == "C1"
        finally:
            fn.release.set()
            b.close()

    def test_default_cannot_evict_default(self):
        fn = _TwoPhase()
        fn.release.clear()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=1, max_wait_ms=1, max_queue=1, pipeline_depth=1,
        )
        try:
            b.submit("w1")
            time.sleep(0.1)
            b.submit("w2")
            time.sleep(0.1)
            f_q = b.submit("q1")  # fills the queue
            with pytest.raises(BatcherOverloaded):
                b.submit("q2")
            assert not f_q.done()  # the queued peer was NOT evicted
        finally:
            fn.release.set()
            b.close()

    def test_near_deadline_slots_selected_first(self):
        """When the backlog exceeds one batch, the nearest-deadline
        slots dispatch first — urgent work must not rot behind slack
        work submitted earlier."""
        fn = _TwoPhase()
        fn.release.clear()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=2, max_wait_ms=1, max_queue=0, pipeline_depth=1,
        )
        try:
            b.submit("w1")
            time.sleep(0.1)
            b.submit("w2")
            time.sleep(0.1)
            # backlog of 3 > max_batch: two slack-deadline slots ahead
            # of one urgent slot in ARRIVAL order
            resilience.set_deadline(resilience.Deadline.after(60.0))
            f_far_a = b.submit("far_a")
            f_far_b = b.submit("far_b")
            resilience.set_deadline(resilience.Deadline.after(5.0))
            f_near = b.submit("near")
            resilience.set_deadline(None)
            fn.release.set()
            for f in (f_far_a, f_far_b, f_near):
                f.result(10)
            # third dispatched batch = the backlog selection: the
            # urgent slot jumped the slack one that arrived before it
            assert "near" in fn.dispatched[2]
            assert fn.dispatched[3] == ["far_b"]
        finally:
            fn.release.set()
            resilience.set_deadline(None)
            b.close()

    def test_fifo_preserved_without_deadlines(self):
        """Deadline-less traffic keeps strict arrival order even when
        the backlog exceeds one batch."""
        fn = _TwoPhase()
        fn.release.clear()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=2, max_wait_ms=1, max_queue=0, pipeline_depth=1,
        )
        try:
            b.submit("w1")
            time.sleep(0.1)
            b.submit("w2")
            time.sleep(0.1)
            futures = [b.submit(f"q{i}") for i in range(5)]
            fn.release.set()
            for f in futures:
                f.result(10)
            backlog_batches = fn.dispatched[2:]
            assert [i for batch in backlog_batches for i in batch] == [
                f"q{i}" for i in range(5)
            ]
        finally:
            fn.release.set()
            b.close()

    def test_retry_after_hint_tracks_backlog(self):
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=4, max_wait_ms=1,
        )
        try:
            assert 0.05 <= b.retry_after_s() <= 5.0
            for i in range(8):
                b.submit(i)
            idle_after = b.retry_after_s()
            assert 0.05 <= idle_after <= 5.0
        finally:
            b.close()


class TestBatchEwmaSettlement:
    """PR 12 regression: the retry-after EWMA fold runs under the cv —
    settlement happens on the completer OR the collector (dispatch
    failure / serial fallback), so the read-modify-write raced with
    itself and with retry_after_s() readers before the fix."""

    def test_concurrent_settlement_and_hint_reads(self):
        fn = _TwoPhase()
        b = MicroBatcher(
            TwoPhaseBatchFn(fn.dispatch, fn.collect),
            max_batch=4, max_wait_ms=1,
        )
        errors: list[BaseException] = []
        stop = threading.Event()

        def settle(value):
            try:
                while not stop.is_set():
                    b._observe_batch_time(value)
            except BaseException as e:  # noqa: BLE001 - fail the test
                errors.append(e)

        def read_hint():
            try:
                while not stop.is_set():
                    hint = b.retry_after_s()
                    assert 0.05 <= hint <= 5.0
            except BaseException as e:  # noqa: BLE001 - fail the test
                errors.append(e)

        threads = [
            threading.Thread(target=settle, args=(0.2,), daemon=True),
            threading.Thread(target=settle, args=(0.4,), daemon=True),
            threading.Thread(target=read_hint, daemon=True),
        ]
        try:
            [t.start() for t in threads]
            time.sleep(0.3)
            stop.set()
            [t.join(timeout=5) for t in threads]
            assert errors == []
            # the fold only ever mixes the two sample values, so the
            # EWMA must land between them — a torn/lost update pattern
            # that escapes the guard shows up as an out-of-range value
            assert 0.2 <= b._batch_ewma_s <= 0.4
        finally:
            b.close()


class TestTenantAttribution:
    """Per-tenant cost attribution (docs/observability.md "Cost
    attribution"): each batch's measured device time is apportioned
    across its slots by slot count, so the per-tenant counters sum to
    exactly the batcher's total measured device time."""

    def _attributed(self, data):
        fam = data.get("pio_tenant_device_seconds_total") or {}
        return {
            s["labels"]["tenant"]: s["value"]
            for s in fam.get("samples") or []
        }

    def _measured_total(self, data):
        # the exported histogram sums round at 1e-6: slot the batch fn
        # a couple ms of work so the 1% tolerance dominates rounding
        return (
            data["pio_device_enqueue_seconds"]["samples"][0]["sum"]
            + data["pio_device_sync_seconds"]["samples"][0]["sum"]
        )

    def test_device_seconds_conserved_across_tenants(self):
        from predictionio_tpu.serving import admission

        def batch_fn(items):
            time.sleep(0.002)
            return [i * 2 for i in items]

        reg = MetricRegistry()
        b = MicroBatcher(
            batch_fn, max_batch=4, max_wait_ms=2, registry=reg,
        )
        try:
            futures = []
            for i in range(24):
                with admission.tenant(f"t{i % 3}"):
                    futures.append(b.submit(i))
            assert [f.result(5) for f in futures] == [
                i * 2 for i in range(24)
            ]
        finally:
            b.close()
        data = reg.to_dict()
        per_tenant = self._attributed(data)
        assert set(per_tenant) == {"t0", "t1", "t2"}
        # conservation: attribution is an exact partition of the
        # measured device time, not a second measurement of it
        assert sum(per_tenant.values()) == pytest.approx(
            self._measured_total(data), rel=0.01
        )
        requests = {
            (s["labels"]["tenant"], s["labels"]["status"]): s["value"]
            for s in data["pio_tenant_requests_total"]["samples"]
        }
        assert sum(requests.values()) == 24.0
        assert all(status == "ok" for _, status in requests)
        waits = {
            s["labels"]["tenant"]: s["count"]
            for s in data["pio_tenant_queue_wait_seconds"]["samples"]
        }
        assert waits == {"t0": 8, "t1": 8, "t2": 8}

    def test_failed_batches_still_attributed(self):
        from predictionio_tpu.serving import admission

        def boom(items):
            time.sleep(0.002)
            raise ValueError("injected batch failure")

        reg = MetricRegistry()
        b = MicroBatcher(boom, max_batch=4, max_wait_ms=2, registry=reg)
        try:
            with admission.tenant("t-err"):
                futures = [b.submit(i) for i in range(4)]
            for f in futures:
                with pytest.raises(ValueError):
                    f.result(5)
        finally:
            b.close()
        data = reg.to_dict()
        per_tenant = self._attributed(data)
        # a failed batch burned real device/host time — it must be
        # charged, or the books don't balance
        assert set(per_tenant) == {"t-err"}
        assert sum(per_tenant.values()) == pytest.approx(
            self._measured_total(data), rel=0.01
        )
        requests = {
            s["labels"]["status"]: s["value"]
            for s in data["pio_tenant_requests_total"]["samples"]
        }
        assert requests == {"error": 4.0}

    def test_anonymous_requests_charge_the_empty_tenant(self):
        reg = MetricRegistry()
        b = MicroBatcher(
            lambda items: items, max_batch=2, max_wait_ms=2,
            registry=reg,
        )
        try:
            [f.result(5) for f in [b.submit(i) for i in range(2)]]
        finally:
            b.close()
        per_tenant = self._attributed(reg.to_dict())
        assert set(per_tenant) == {""}

    def test_noisy_neighbor_requires_overuse_and_harm(self):
        from predictionio_tpu.obs import timeline as timeline_mod
        from predictionio_tpu.serving.batching import _NoisyRollup

        reg = MetricRegistry()
        gauge = reg.gauge("pio_tenant_noisy", "h", ("tenant",))
        ring = timeline_mod.Timeline(capacity=16)
        previous = timeline_mod.set_timeline(ring)
        try:
            roll = _NoisyRollup(gauge)
            # hog takes ~5x the fair share AND the victim breaches its
            # queue-wait SLO -> flagged at window rollover
            roll.observe("hog", 5.0, 0.0)
            roll.observe("victim", 1.0, roll.wait_slo_s * 2)
            roll.window_end = 0.0  # force the rollover
            roll.observe("victim", 0.0, 0.0)
            flags = {
                s["labels"]["tenant"]: s["value"]
                for s in reg.to_dict()["pio_tenant_noisy"]["samples"]
            }
            assert flags.get("hog") == 1.0
            assert "victim" not in flags or flags["victim"] == 0.0
            kinds = [e["kind"] for e in ring.events()]
            assert "noisy_neighbor" in kinds
            # overuse with NO harmed neighbor (nobody breached the
            # wait SLO) clears the flag at the next rollover
            roll.observe("hog", 5.0, 0.0)
            roll.observe("victim", 1.0, 0.0)
            roll.window_end = 0.0
            roll.observe("victim", 0.0, 0.0)
            flags = {
                s["labels"]["tenant"]: s["value"]
                for s in reg.to_dict()["pio_tenant_noisy"]["samples"]
            }
            assert flags.get("hog") == 0.0
        finally:
            timeline_mod.set_timeline(previous)
