"""Telemetry hygiene: spans closed on all paths, metric names
registered with one consistent (kind, label-set) project-wide.

``span-leak``: a span context manager (``tracer.trace(...)``,
``tracer.child(...)``, ``tracing.span(...)``) or raw ``tracing.Span``
construction must reach a ``with`` statement — directly, via a variable
later used as a ``with`` context expression in the same function (the
``span_cm = ... ; with span_cm:`` pattern), or by being returned to the
caller. Anything else can leak an open span on an exception path, which
pins the trace in the recorder's open table until eviction.

``metric-labels``: ``registry.counter/gauge/histogram(name, ...)``
sites are collected project-wide; a metric name registered with two
different label tuples (or two different kinds) would raise at runtime
*only if* both sites ever run in one process — the lint catches the
conflict statically.
"""

from __future__ import annotations

import ast
from collections import defaultdict

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.model import Finding
from predictionio_tpu.analysis.source import SourceModule

_METRIC_KINDS = {"counter", "gauge", "histogram"}


# -- span-leak -------------------------------------------------------------

def _span_call_desc(call: ast.Call) -> str | None:
    func = call.func
    dotted = astutil.dotted_name(func)
    if dotted == "tracing.span":
        return "tracing.span(...)"
    if dotted == "tracing.Span":
        return "tracing.Span(...)"
    if isinstance(func, ast.Attribute):
        recv = astutil.dotted_name(func.value) or ""
        if func.attr in ("trace", "child") and "tracer" in recv.lower():
            return f"{recv}.{func.attr}(...)"
    return None


def _reaches_with(call: ast.Call, fn: ast.AST | None) -> bool:
    """The call result is used as a context manager or returned."""
    node: ast.AST = call
    parent = astutil.parent_of(node)
    while parent is not None:
        if isinstance(parent, ast.withitem):
            return _contains(parent.context_expr, call)
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            for item in parent.items:
                if _contains(item.context_expr, call):
                    return True
            return False  # inside a with *body* doesn't count
        if isinstance(parent, ast.Return):
            return True  # factory pattern: caller owns the lifecycle
        if isinstance(parent, ast.Assign):
            names = [
                t.id for t in parent.targets if isinstance(t, ast.Name)
            ]
            return any(
                _name_used_in_with(fn, name) for name in names
            )
        if isinstance(parent, (ast.IfExp, ast.BoolOp)):
            node, parent = parent, astutil.parent_of(parent)
            continue
        return False
    return False


def _contains(root: ast.AST, needle: ast.AST) -> bool:
    return any(n is needle for n in ast.walk(root))


def _name_used_in_with(fn: ast.AST | None, name: str) -> bool:
    if fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
    return False


# -- metric labels ---------------------------------------------------------

def _metric_site(call: ast.Call):
    """(kind, name, labels-or-None) for registry.counter/gauge/histogram
    calls with a literal metric name; labels None when dynamic."""
    func = call.func
    if not (
        isinstance(func, ast.Attribute) and func.attr in _METRIC_KINDS
    ):
        return None
    recv = (astutil.dotted_name(func.value) or "").lower()
    if "registry" not in recv and "metrics" not in recv:
        return None
    if not call.args or not (
        isinstance(call.args[0], ast.Constant)
        and isinstance(call.args[0].value, str)
    ):
        return None
    name = call.args[0].value
    labels_node = None
    if len(call.args) >= 3:
        labels_node = call.args[2]
    for kw in call.keywords:
        if kw.arg == "label_names":
            labels_node = kw.value
    if labels_node is None:
        labels: tuple | None = ()
    elif isinstance(labels_node, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) for e in labels_node.elts
    ):
        labels = tuple(e.value for e in labels_node.elts)
    else:
        labels = None  # dynamic — can't check
    return func.attr, name, labels


def check(modules: list[SourceModule]) -> list[Finding]:
    findings: list[Finding] = []
    #: metric name -> list of (kind, labels, mod, line, ctx)
    metric_sites: dict[str, list] = defaultdict(list)

    for mod in modules:
        if mod.rel_path.startswith("predictionio_tpu/obs/"):
            in_obs = True  # the tracing/registry layer itself is exempt
        else:
            in_obs = False
        index = mod.index()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            site = _metric_site(node)
            if site is not None:
                kind, name, labels = site
                metric_sites[name].append(
                    (kind, labels, mod, node.lineno,
                     index.context_of(node))
                )
            if in_obs:
                continue
            desc = _span_call_desc(node)
            if desc is None:
                continue
            ctx = index.context_of(node)
            fn = index.funcs.get(ctx)
            if _reaches_with(node, fn):
                continue
            findings.append(
                Finding(
                    rule="span-leak",
                    path=mod.rel_path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{desc} is not used as a context manager — "
                        "the span may never close"
                    ),
                    context=ctx,
                    source=mod.source_line(node.lineno),
                )
            )

    for name, sites in metric_sites.items():
        kinds = {kind for kind, _l, _m, _n, _c in sites}
        label_sets = {
            labels for _k, labels, _m, _n, _c in sites
            if labels is not None
        }
        if len(kinds) <= 1 and len(label_sets) <= 1:
            continue
        detail = "; ".join(
            f"{m.rel_path}:{line} {kind}{list(labels) if labels is not None else '<dynamic>'}"
            for kind, labels, m, line, _c in sites
        )
        for kind, labels, mod, line, ctx in sites:
            findings.append(
                Finding(
                    rule="metric-labels",
                    path=mod.rel_path,
                    line=line,
                    col=0,
                    message=(
                        f"metric {name!r} registered inconsistently "
                        f"({detail})"
                    ),
                    context=ctx,
                    source=mod.source_line(line),
                )
            )
    return findings
