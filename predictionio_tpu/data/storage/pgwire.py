"""Vendored pure-Python PostgreSQL driver (wire protocol v3, DB-API 2.0).

The reference's production store needs a JDBC driver jar on the
classpath (``data/.../storage/jdbc/JDBCUtils.scala:26-46`` —
``driverType`` picks org.postgresql.Driver / mysql Driver); the Python
analogue would be "pip install psycopg2", which this environment (and
many locked-down TPU pods) cannot do. This module removes the
dependency: a minimal DB-API driver speaking the PostgreSQL frontend/
backend protocol v3 over a plain socket, implementing exactly what
:mod:`predictionio_tpu.data.storage.sql_common` needs:

* startup + auth: trust, cleartext password, MD5, SCRAM-SHA-256
* the simple query protocol with client-side parameter interpolation
  (``format``/``%s`` paramstyle, like psycopg2)
* text-format result decoding by type OID (ints, floats, bool, bytea)
* explicit transactions (lazy BEGIN; ``commit``/``rollback``)
* the DB-API exception hierarchy mapped from SQLSTATE classes

Not implemented (not needed here): extended query protocol, COPY,
LISTEN/NOTIFY, SSL negotiation, binary format.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
from typing import Any, Iterable, Sequence

apilevel = "2.0"
threadsafety = 1  # module-level sharing only; one connection per thread
paramstyle = "format"


# -- DB-API exceptions ------------------------------------------------------


class Error(Exception):
    """Base DB-API error; carries the server's SQLSTATE when known."""

    def __init__(self, msg: str, sqlstate: str | None = None):
        super().__init__(msg)
        self.sqlstate = sqlstate


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


Warning = type("Warning", (Exception,), {})  # noqa: A001 - DB-API name
DataError = type("DataError", (DatabaseError,), {})


def _error_for(sqlstate: str, msg: str) -> DatabaseError:
    """Map an SQLSTATE class to the DB-API exception hierarchy
    (class 23 integrity, 42 syntax/undefined-object, else operational)."""
    if sqlstate.startswith("23"):
        return IntegrityError(msg, sqlstate)
    if sqlstate.startswith(("42", "26")):
        return ProgrammingError(msg, sqlstate)
    return OperationalError(msg, sqlstate)


# -- literal quoting (client-side interpolation, %s paramstyle) -------------


def quote(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"'\\x{bytes(value).hex()}'::bytea"
    if isinstance(value, str):
        # standard_conforming_strings=on (server default since 9.1):
        # backslash is literal, only the quote needs doubling
        return "'" + value.replace("'", "''") + "'"
    raise ProgrammingError(f"cannot adapt parameter of type {type(value)}")


def interpolate(sql: str, params: Sequence[Any]) -> str:
    if not params:
        return sql
    parts = sql.split("%s")
    if len(parts) != len(params) + 1:
        raise ProgrammingError(
            f"statement has {len(parts) - 1} placeholders but "
            f"{len(params)} parameters were supplied"
        )
    out = [parts[0]]
    for part, p in zip(parts[1:], params):
        out.append(quote(p))
        out.append(part)
    return "".join(out)


# -- text-format value decoding by OID --------------------------------------

_INT_OIDS = {20, 21, 23, 26, 28}  # int8/int2/int4/oid/xid
_FLOAT_OIDS = {700, 701, 1700}  # float4/float8/numeric
_BYTEA_OID = 17
_BOOL_OID = 16


def _decode(raw: bytes | None, oid: int) -> Any:
    if raw is None:
        return None
    if oid in _INT_OIDS:
        return int(raw)
    if oid in _FLOAT_OIDS:
        return float(raw)
    if oid == _BOOL_OID:
        return raw == b"t"
    if oid == _BYTEA_OID:
        text = raw.decode("ascii")
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        # legacy octal escape format
        return text.encode("latin-1").decode("unicode_escape").encode(
            "latin-1"
        )
    return raw.decode("utf-8")


# -- SCRAM-SHA-256 (RFC 7677, the modern postgres default auth) -------------


class _Scram:
    def __init__(self, user: str, password: str):
        self._password = password.encode("utf-8")
        self._nonce = base64.b64encode(os.urandom(18)).decode("ascii")
        # channel-binding not attempted over a plain socket → gs2 "n,,"
        self.client_first = f"n,,n=,r={self._nonce}".encode("ascii")
        self._client_first_bare = f"n=,r={self._nonce}"

    def client_final(self, server_first: bytes) -> bytes:
        fields = dict(
            kv.split("=", 1) for kv in server_first.decode("ascii").split(",")
        )
        r, s, i = fields["r"], fields["s"], int(fields["i"])
        if not r.startswith(self._nonce):
            raise OperationalError("SCRAM: server nonce mismatch")
        salted = hashlib.pbkdf2_hmac(
            "sha256", self._password, base64.b64decode(s), i
        )
        client_key = hmac.digest(salted, b"Client Key", "sha256")
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c=biws,r={r}"
        auth_msg = ",".join(
            (
                self._client_first_bare,
                server_first.decode("ascii"),
                without_proof,
            )
        ).encode("ascii")
        sig = hmac.digest(stored_key, auth_msg, "sha256")
        proof = bytes(a ^ b for a, b in zip(client_key, sig))
        server_key = hmac.digest(salted, b"Server Key", "sha256")
        self._server_sig = base64.b64encode(
            hmac.digest(server_key, auth_msg, "sha256")
        ).decode("ascii")
        return (
            without_proof + ",p=" + base64.b64encode(proof).decode("ascii")
        ).encode("ascii")

    def verify_server_final(self, server_final: bytes) -> None:
        fields = dict(
            kv.split("=", 1) for kv in server_final.decode("ascii").split(",")
        )
        if fields.get("v") != self._server_sig:
            raise OperationalError("SCRAM: bad server signature")


# -- protocol plumbing ------------------------------------------------------


#: sanity ceiling on a single backend message (1 GiB); a frame length
#: outside [4, MAX] is a corrupt or hostile stream, not a big result
_MAX_FRAME = 1 << 30


class _Wire:
    """Framed reads/writes of protocol v3 messages."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def send(self, type_byte: bytes, payload: bytes) -> None:
        self._sock.sendall(
            type_byte + struct.pack("!I", len(payload) + 4) + payload
        )

    def send_startup(self, payload: bytes) -> None:
        self._sock.sendall(struct.pack("!I", len(payload) + 4) + payload)

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise OperationalError("server closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv(self) -> tuple[bytes, bytes]:
        header = self._read_exact(5)
        (length,) = struct.unpack("!I", header[1:5])
        # the length field counts itself (>=4); reject nonsense before
        # it turns into a negative read or an unbounded buffer
        if not 4 <= length <= _MAX_FRAME:
            raise OperationalError(
                f"protocol violation: frame length {length} out of range"
            )
        return header[:1], self._read_exact(length - 4)


def _parse_error(payload: bytes) -> DatabaseError:
    fields: dict[bytes, str] = {}
    for part in payload.split(b"\x00"):
        if part:
            fields[part[:1]] = part[1:].decode("utf-8", "replace")
    sqlstate = fields.get(b"C", "58000")
    msg = fields.get(b"M", "unknown server error")
    return _error_for(sqlstate, f"{msg} [SQLSTATE {sqlstate}]")


class Connection:
    def __init__(
        self,
        host: str = "localhost",
        port: int = 5432,
        database: str = "postgres",
        user: str = "postgres",
        password: str = "",
        connect_timeout: float = 10.0,
    ):
        self._closed = False
        self._in_tx = False
        try:
            sock = socket.create_connection(
                (host, port), timeout=connect_timeout
            )
        except OSError as exc:
            self._closed = True
            raise OperationalError(
                f"cannot connect to {host}:{port}: {exc}"
            ) from exc
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wire = _Wire(sock)
        self._sock = sock
        try:
            self._handshake(database, user, password)
        except BaseException:
            self.close()
            raise

    # -- session startup ---------------------------------------------------
    def _handshake(self, database: str, user: str, password: str) -> None:
        params = (
            b"user\x00" + user.encode() + b"\x00"
            b"database\x00" + database.encode() + b"\x00"
            b"client_encoding\x00UTF8\x00\x00"
        )
        self._wire.send_startup(struct.pack("!I", 196608) + params)  # 3.0
        scram: _Scram | None = None
        while True:
            mtype, payload = self._wire.recv()
            if mtype == b"E":
                raise _parse_error(payload)
            if mtype == b"R":
                (code,) = struct.unpack("!I", payload[:4])
                if code == 0:  # AuthenticationOk
                    continue
                if code == 3:  # cleartext
                    self._wire.send(b"p", password.encode() + b"\x00")
                elif code == 5:  # md5(md5(password+user)+salt)
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()
                    ).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt
                    ).hexdigest()
                    self._wire.send(b"p", b"md5" + digest.encode() + b"\x00")
                elif code == 10:  # SASL: pick SCRAM-SHA-256
                    mechs = payload[4:].split(b"\x00")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise NotSupportedError(
                            f"server offers no supported SASL mechanism: "
                            f"{mechs}"
                        )
                    scram = _Scram(user, password)
                    first = scram.client_first
                    self._wire.send(
                        b"p",
                        b"SCRAM-SHA-256\x00"
                        + struct.pack("!I", len(first))
                        + first,
                    )
                elif code == 11:  # SASLContinue
                    assert scram is not None
                    self._wire.send(b"p", scram.client_final(payload[4:]))
                elif code == 12:  # SASLFinal
                    assert scram is not None
                    scram.verify_server_final(payload[4:])
                else:
                    raise NotSupportedError(
                        f"unsupported authentication request {code}"
                    )
            elif mtype == b"Z":  # ReadyForQuery
                return
            # S (ParameterStatus), K (BackendKeyData), N (Notice): ignore

    # -- query execution ---------------------------------------------------
    def _query(self, sql: str) -> tuple[list, list, int]:
        """Run one simple-protocol query; returns (columns, rows, rowcount).

        The query string may contain several ``;``-separated statements
        (the simple protocol runs them in one round trip — how
        ``executemany`` amortizes network latency); ``rowcount`` is then
        the SUM of the per-statement affected-row counts.
        """
        if self._closed:
            raise InterfaceError("connection is closed")
        self._wire.send(b"Q", sql.encode("utf-8") + b"\x00")
        columns: list[tuple[str, int]] = []
        rows: list[tuple] = []
        rowcount = -1
        error: DatabaseError | None = None
        while True:
            mtype, payload = self._wire.recv()
            if mtype == b"T":  # RowDescription
                (n,) = struct.unpack("!H", payload[:2])
                off, columns = 2, []
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    name = payload[off:end].decode("utf-8")
                    table_oid, attnum, type_oid, size, mod, fmt = (
                        struct.unpack("!IHIhih", payload[end + 1:end + 19])
                    )
                    columns.append((name, type_oid))
                    off = end + 19
            elif mtype == b"D":  # DataRow
                (n,) = struct.unpack("!H", payload[:2])
                off, vals = 2, []
                for i in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln < 0:
                        raw = None
                    else:
                        raw = payload[off:off + ln]
                        off += ln
                    vals.append(_decode(raw, columns[i][1]))
                rows.append(tuple(vals))
            elif mtype == b"C":  # CommandComplete: e.g. "INSERT 0 3"
                tag = payload.rstrip(b"\x00").decode("ascii")
                tail = tag.rsplit(" ", 1)[-1]
                if tail.isdigit():
                    rowcount = (
                        int(tail) if rowcount < 0 else rowcount + int(tail)
                    )
            elif mtype == b"E":
                error = _parse_error(payload)
            elif mtype == b"Z":
                if error is not None:
                    raise error
                return columns, rows, rowcount
            # I (EmptyQueryResponse), N (Notice), S (ParameterStatus): skip

    def _exec_tx(self, sql: str) -> tuple[list, list, int]:
        if not self._in_tx:
            self._query("BEGIN")
            self._in_tx = True
        return self._query(sql)

    # -- DB-API surface ----------------------------------------------------
    def cursor(self) -> "Cursor":
        return Cursor(self)

    def commit(self) -> None:
        if self._in_tx:
            self._query("COMMIT")
            self._in_tx = False

    def rollback(self) -> None:
        if self._in_tx:
            try:
                self._query("ROLLBACK")
            finally:
                self._in_tx = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._sock.sendall(b"X" + struct.pack("!I", 4))
            except OSError:
                pass
            self._sock.close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self._conn = conn
        self.description: list | None = None
        self.rowcount = -1
        self._rows: list[tuple] = []
        self._idx = 0

    def execute(self, sql: str, params: Sequence[Any] = ()) -> "Cursor":
        columns, rows, rowcount = self._conn._exec_tx(
            interpolate(sql, tuple(params))
        )
        self.description = (
            [(name, oid, None, None, None, None, None) for name, oid in columns]
            or None
        )
        self._rows, self._idx, self.rowcount = rows, 0, rowcount
        return self

    #: statements per round trip in executemany (bounds message size)
    EXECUTEMANY_CHUNK = 200

    def executemany(
        self, sql: str, seq_of_params: Iterable[Sequence[Any]]
    ) -> "Cursor":
        """Interpolate every row and ship them in ``;``-joined groups —
        one network round trip per EXECUTEMANY_CHUNK statements instead
        of one per row (the simple protocol runs a multi-statement
        Query atomically within the surrounding transaction)."""
        import itertools

        stmt_iter = (
            interpolate(sql, tuple(params)) for params in seq_of_params
        )
        total = 0
        while True:
            chunk = list(
                itertools.islice(stmt_iter, self.EXECUTEMANY_CHUNK)
            )
            if not chunk:
                break
            _cols, _rows, count = self._conn._exec_tx(";".join(chunk))
            if count > 0:
                total += count
        self.description = None
        self._rows, self._idx = [], 0
        self.rowcount = total
        return self

    def fetchone(self):
        if self._idx >= len(self._rows):
            return None
        row = self._rows[self._idx]
        self._idx += 1
        return row

    def fetchmany(self, size: int | None = None):
        size = size or self.arraysize
        out = self._rows[self._idx:self._idx + size]
        self._idx += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._idx:]
        self._idx = len(self._rows)
        return out

    def close(self) -> None:
        self._rows = []

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row


def connect(**kwargs) -> Connection:
    return Connection(**kwargs)
